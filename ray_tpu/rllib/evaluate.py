"""`rllib evaluate` CLI (reference: rllib/evaluate.py): restore an
algorithm from a checkpoint directory and run greedy in-env episodes.

Usage::

    python -m ray_tpu.rllib.evaluate /tmp/ckpt --algo PPO \
        --env CartPole-v1 --steps 2000
"""
from __future__ import annotations

import argparse
import json


def evaluate_checkpoint(checkpoint_path: str, algo: str, env: str,
                        config: dict | None = None,
                        num_steps: int = 1000) -> dict:
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.rllib import get_algorithm_config
    from ray_tpu.rllib.train import apply_config

    cfg = get_algorithm_config(algo).environment(env)
    apply_config(cfg, config or {})
    algorithm = cfg.build()
    algorithm.load_checkpoint(Checkpoint.from_directory(checkpoint_path))
    out = algorithm.evaluate(num_steps=num_steps)
    algorithm.stop()
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rllib evaluate", description=__doc__)
    p.add_argument("checkpoint", help="checkpoint directory")
    p.add_argument("--algo", "--run", dest="algo", required=True)
    p.add_argument("--env", required=True)
    p.add_argument("--config", default="{}",
                   help="JSON dict of AlgorithmConfig overrides")
    p.add_argument("--steps", type=int, default=1000)
    args = p.parse_args(argv)
    out = evaluate_checkpoint(args.checkpoint, args.algo, args.env,
                              json.loads(args.config), args.steps)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
