"""Connectors: typed, serializable obs/action preprocessing pipelines.

Reference: rllib/connectors/connector.py:83,141 (Connector/AgentConnector
with to_state/from_state for checkpointing) + agent/action pipelines.
These make a policy deployable without the sampling stack.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.utils.filters import MeanStdFilter


class Connector:
    def __call__(self, data: Any) -> Any:
        raise NotImplementedError

    def to_state(self) -> Tuple[str, Any]:
        return type(self).__name__, None

    @staticmethod
    def from_state(name: str, state: Any) -> "Connector":
        cls = _REGISTRY[name]
        return cls._from_state(state)

    @classmethod
    def _from_state(cls, state):
        return cls()


class FlattenObs(Connector):
    def __call__(self, obs):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1) if obs.ndim > 2 else obs


class ClipReward(Connector):
    def __init__(self, limit: float = 1.0):
        self.limit = limit

    def __call__(self, reward):
        return np.clip(reward, -self.limit, self.limit)

    def to_state(self):
        return "ClipReward", self.limit

    @classmethod
    def _from_state(cls, state):
        return cls(state)


class NormalizeObs(Connector):
    """Mean-std filter connector (cross-worker syncable via filter deltas)."""

    def __init__(self, shape: Tuple[int, ...]):
        self.filter = MeanStdFilter(shape)

    def __call__(self, obs):
        return self.filter(np.asarray(obs))

    def to_state(self):
        st = self.filter.stat
        return "NormalizeObs", {
            "shape": self.filter.shape, "n": st.n,
            "mean": st.mean.tolist(), "m2": st.m2.tolist()}

    @classmethod
    def _from_state(cls, state):
        c = cls(tuple(state["shape"]))
        c.filter.stat.n = state["n"]
        c.filter.stat.mean = np.asarray(state["mean"])
        c.filter.stat.m2 = np.asarray(state["m2"])
        return c


class ClipAction(Connector):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, action):
        return np.clip(action, self.low, self.high)

    def to_state(self):
        return "ClipAction", (self.low, self.high)

    @classmethod
    def _from_state(cls, state):
        return cls(*state)


class ConnectorPipeline(Connector):
    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, data):
        for c in self.connectors:
            data = c(data)
        return data

    def to_state(self):
        return "ConnectorPipeline", [c.to_state() for c in self.connectors]

    @classmethod
    def _from_state(cls, state):
        return cls([Connector.from_state(n, s) for n, s in state])


_REGISTRY: Dict[str, type] = {
    c.__name__: c for c in
    (FlattenObs, ClipReward, NormalizeObs, ClipAction, ConnectorPipeline)
}
