"""Offline RL: sample IO, behavior cloning, off-policy evaluation.

Reference: rllib/offline/ — JsonWriter/JsonReader (json_writer.py:30,
json_reader.py:43), the BC algorithm (rllib/algorithms/bc/bc.py) and the
OPE estimators (offline/estimators/importance_sampling.py,
weighted_importance_sampling.py).  The IO format matches the reference's
spirit: one JSON object per line, arrays as nested lists, so files are
greppable and language-neutral.
"""
from ray_tpu.rllib.offline.io import JsonReader, JsonWriter  # noqa: F401
from ray_tpu.rllib.offline.estimators import (  # noqa: F401
    ImportanceSampling,
    WeightedImportanceSampling,
)
