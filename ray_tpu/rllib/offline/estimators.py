"""Off-policy evaluation estimators.

Reference: rllib/offline/estimators/importance_sampling.py:14 and
weighted_importance_sampling.py:16 — estimate the target policy's episode
return from behavior-policy data via per-step likelihood ratios.

Inputs are episodes: each a SampleBatch carrying ``rewards``,
``action_logp`` (behavior policy log-probs at sampling time) and the
TARGET policy's log-probs for the same (obs, action) pairs, supplied by a
``target_logp_fn(batch) -> [T] array``.  Math is vectorized numpy — the
estimators run driver-side on modest data.
"""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class _Estimator:
    def __init__(self, gamma: float = 1.0):
        self.gamma = gamma

    def _ratios_and_returns(self, episodes: List[SampleBatch],
                            target_logp_fn: Callable):
        """Per-episode cumulative ratio rho_{0:T} and discounted return."""
        rhos, rets = [], []
        for ep in episodes:
            t_logp = np.asarray(target_logp_fn(ep), dtype=np.float64)
            b_logp = np.asarray(ep["action_logp"], dtype=np.float64)
            # Product of per-step ratios, in log space for stability.
            rhos.append(np.exp(np.sum(t_logp - b_logp)))
            r = np.asarray(ep["rewards"], dtype=np.float64)
            disc = self.gamma ** np.arange(len(r))
            rets.append(float(np.sum(r * disc)))
        return np.asarray(rhos), np.asarray(rets)

    def estimate(self, episodes: List[SampleBatch],
                 target_logp_fn: Callable) -> Dict[str, float]:
        raise NotImplementedError


class ImportanceSampling(_Estimator):
    """V^pi ≈ mean(rho_ep * return_ep) — unbiased, high variance."""

    def estimate(self, episodes, target_logp_fn) -> Dict[str, float]:
        rhos, rets = self._ratios_and_returns(episodes, target_logp_fn)
        vals = rhos * rets
        return {"v_target": float(vals.mean()),
                "v_behavior": float(rets.mean()),
                "v_gain": float(vals.mean() / rets.mean())
                if rets.mean() else float("nan"),
                "std": float(vals.std())}


class WeightedImportanceSampling(_Estimator):
    """V^pi ≈ sum(rho_ep * return_ep) / sum(rho_ep) — biased, lower
    variance (self-normalized)."""

    def estimate(self, episodes, target_logp_fn) -> Dict[str, float]:
        rhos, rets = self._ratios_and_returns(episodes, target_logp_fn)
        denom = rhos.sum()
        v = float((rhos * rets).sum() / denom) if denom > 0 else float("nan")
        return {"v_target": v,
                "v_behavior": float(rets.mean()),
                "v_gain": v / float(rets.mean()) if rets.mean()
                else float("nan"),
                "effective_sample_size":
                    float(denom ** 2 / np.maximum((rhos ** 2).sum(), 1e-12))}
