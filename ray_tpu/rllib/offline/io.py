"""SampleBatch JSON-lines IO (reference: rllib/offline/json_writer.py:30,
json_reader.py:43)."""
from __future__ import annotations

import glob
import json
import os
from typing import Iterator, List, Optional, Union

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class JsonWriter:
    """Append SampleBatches to JSON-lines files under ``path``."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_file_size = max_file_size
        self._index = 0
        self._f = None

    def _rotate(self):
        if self._f is not None:
            self._f.close()
        name = os.path.join(self.path, f"output-{self._index:05d}.json")
        self._index += 1
        self._f = open(name, "a")

    def write(self, batch: SampleBatch):
        if self._f is None or self._f.tell() > self.max_file_size:
            self._rotate()
        payload = {k: np.asarray(v).tolist() for k, v in batch.items()}
        self._f.write(json.dumps(payload) + "\n")
        self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


class JsonReader:
    """Iterate SampleBatches back from JsonWriter output."""

    def __init__(self, path: Union[str, List[str]]):
        if isinstance(path, str):
            if os.path.isdir(path):
                self.files = sorted(glob.glob(os.path.join(path, "*.json")))
            else:
                self.files = sorted(glob.glob(path)) or [path]
        else:
            self.files = list(path)

    def read_all(self) -> SampleBatch:
        return SampleBatch.concat_samples(list(self))

    def __iter__(self) -> Iterator[SampleBatch]:
        for fp in self.files:
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        obj = json.loads(line)
                        yield SampleBatch({k: np.asarray(v)
                                           for k, v in obj.items()})
