"""Execution-plane building blocks for the rllib stack (reference:
rllib/execution/ — replay ops, learner threads, rollout ops).  Here the
package holds the distributed replay plane (replay_plane.py)."""
from ray_tpu.rllib.execution.replay_plane import (  # noqa: F401
    ReplayBatch,
    ReplayPlane,
    ReplayShard,
    ShardCore,
    compute_nstep,
    run_actor_replay_iter,
)
