"""Distributed replay plane: sharded prioritized replay whose storage IS
the object plane.

The learner-local ``HostReplay`` ring (pre-PR-18 dqn.py) made every
rollout transition travel worker -> learner as raw bytes, and sampling
ran serial with SGD on the learner thread.  Here replay becomes a
throughput datapath assembled from planes this repo already has
(pooled shm segments, fragment refs, the flow substrate, the WorkerSet
strike machinery) — the Ray design's canonical object-store workload
(arXiv:1712.05889 §4.2) in the Podracer actor/learner decoupling
(arXiv:2104.06272):

- **Zero-copy insert** — rollout workers ``put_many`` their fixed-shape
  fragment columns (one pooled-segment write; one ``seal_batch`` control
  message) and ship only the REFS.  A :class:`ReplayShard` actor indexes
  and pins refs — payload bytes never enter the shard or the learner's
  insert path.  Eviction is a ref release: the ring slot drops its
  ObjectRef and the store reclaims the segment into the pool.
- **Vectorized priorities** — each shard keeps sum/min segment trees
  over per-transition priorities (leaf = ``slot * frag_len + offset``)
  using the batched ``set_many`` / ``find_prefixsum_idx_many`` ops from
  rllib/utils/replay_buffers.py: one numpy descent per sampled batch,
  one propagation wave per priority-update batch.
- **Two-level sampling, one gather** — a batch draw picks shards by a
  multinomial over their priority masses, then each shard runs an
  in-shard prefix-sum search; the learner resolves every sampled
  fragment column with ONE batched ``get_many`` and assembles
  compile-once ``[B, ...]`` batches (fixed B, stable dtypes — the jit
  signature never changes).
- **Async priority updates** — learner TD errors flow back as coalesced
  batches on a bounded ``flow.Stage`` sink: pending updates merge into
  one RPC per shard per send, the bounded queue backpressures a learner
  that outruns the plane, and updates addressed to evicted slots are
  dropped by a per-slot sequence check (staleness-tolerant by design).
- **Weight-version stamps** — every fragment carries the weights version
  it was acted under (the PR 5 stamp); sampled batches expose per-row
  versions and a ``max_weight_staleness`` gate masks over-stale rows'
  importance weights to zero without changing the batch shape.
- **Gather/SGD overlap** — :meth:`ReplayPlane.prefetch` returns a
  ``flow.Stage`` that keeps K gathered batches in flight, so the
  gather + host assembly of batch i+1 runs while the learner's SGD step
  consumes batch i (tools/perf_smoke.run_replay_smoke proves it with
  wall stamps).
- **Shard death** — shards live behind the existing WorkerSet strike
  machinery: a failed RPC strikes the shard, a struck-out shard is
  replaced (empty) and the missing draw mass is re-spread over the
  survivors, so sampling degrades gracefully and the learner never
  loses a step.

``ReplayPlane(num_shards=0)`` is the LOCAL single-shard mode: the same
:class:`ShardCore` runs in-process and payload tokens are the fragment
column dicts themselves — this replaces ``HostReplay`` so DQN/SAC/TD3
actor modes share one replay implementation (and the RLHF loop can
reuse the plane for preference data).
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu
from ray_tpu.parallel.flow import CancellationToken, Stage, Window
from ray_tpu.rllib.utils.replay_buffers import MinSegmentTree, SumSegmentTree

__all__ = [
    "LEARNER_COLS",
    "ReplayBatch",
    "ReplayPlane",
    "ReplayShard",
    "ShardCore",
    "compute_nstep",
    "run_actor_replay_iter",
]

# The canonical learner minibatch schema (what the TD/actor-critic losses
# consume).  n_step > 1 adds a "discounts" column (gamma^m * (1 - done)).
LEARNER_COLS = ("obs", "actions", "rewards", "next_obs", "dones")

_CLOSE = object()  # priority-update queue end-of-stream sentinel


# ---------------------------------------------------------------------------
# n-step returns at insert, from fragment contiguity
# ---------------------------------------------------------------------------

def compute_nstep(batch: Dict[str, np.ndarray], num_envs: int,
                  gamma: float, n_step: int) -> Dict[str, np.ndarray]:
    """Fold n-step returns into a raw transition fragment.

    ``batch`` holds flat row-major columns where row ``t * num_envs + e``
    is env ``e``'s transition at fragment step ``t`` (the
    OffPolicyRolloutWorker layout), so step t's successor sits exactly
    ``num_envs`` rows ahead — fragment contiguity is the whole index
    structure, no episode ids needed.  The horizon truncates at the
    first ``done`` AND at the fragment end (the last rows bootstrap from
    however many steps the fragment still holds).  Returns a new column
    dict: ``rewards`` become the discounted n-step sums, ``next_obs`` /
    ``dones`` move to the horizon end, and a ``discounts`` column
    carries ``gamma^m * (1 - done_m)`` (m = steps actually folded) — the
    exact bootstrap factor for ``target = R + discount * Q(next_obs)``.
    """
    n = len(batch["rewards"])
    N = int(num_envs) if num_envs else 1
    if n % N != 0:
        raise ValueError(f"fragment of {n} rows is not divisible by "
                         f"num_envs={N}")
    T = n // N
    r = np.asarray(batch["rewards"], np.float64).reshape(T, N)
    d = np.asarray(batch["dones"], np.float64).reshape(T, N)
    next_obs = np.asarray(batch["next_obs"])
    next_obs = next_obs.reshape((T, N) + next_obs.shape[1:])

    R = r.copy()
    nxt = next_obs.copy()
    dfin = d.copy()
    m_steps = np.ones((T, N))
    open_ = 1.0 - d          # horizon still open after folding step t
    gamma_pow = 1.0
    for k in range(1, int(n_step)):
        gamma_pow *= gamma
        ext = open_[:T - k] if T - k > 0 else open_[:0]
        if ext.size == 0:
            break
        R[:T - k] += ext * gamma_pow * r[k:]
        sel = ext > 0
        nxt[:T - k][sel] = next_obs[k:][sel]
        dfin[:T - k][sel] = d[k:][sel]
        m_steps[:T - k] += ext
        new_open = np.zeros_like(open_)
        new_open[:T - k] = ext * (1.0 - d[k:])
        open_ = new_open

    out = dict(batch)
    out["rewards"] = R.reshape(n).astype(np.float32)
    out["next_obs"] = nxt.reshape((n,) + next_obs.shape[2:])
    out["dones"] = dfin.reshape(n).astype(np.float32)
    out["discounts"] = ((gamma ** m_steps) * (1.0 - dfin)).reshape(n) \
        .astype(np.float32)
    return out


# ---------------------------------------------------------------------------
# ShardCore: ring of fragment slots + vectorized priority trees
# ---------------------------------------------------------------------------

class ShardCore:
    """One replay shard: a ring of fixed-shape fragment slots plus
    vectorized sum/min segment trees over per-transition priorities.

    The core never touches payload bytes: each slot holds an opaque
    payload token — the fragment's column dict in local mode, a
    ``{col: ObjectRef}`` dict in the distributed plane — and the
    priority leaf for transition ``(slot, offset)`` is
    ``slot * frag_len + offset``.  Sampling and priority updates run the
    batched tree ops; a per-slot sequence number makes late priority
    updates addressed to an evicted slot drop silently."""

    def __init__(self, capacity: int, alpha: float = 0.0, seed: int = 0,
                 eps: float = 1e-6):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.alpha = float(alpha)
        self.eps = float(eps)
        self.rng = np.random.default_rng(seed)
        self.frag_len: Optional[int] = None
        self.num_slots = 0
        self.slots: List[Optional[Dict[str, Any]]] = []
        self.slot_seq: Optional[np.ndarray] = None
        self._sum: Optional[SumSegmentTree] = None
        self._min: Optional[MinSegmentTree] = None
        self.cursor = 0
        self.size = 0
        self.max_priority = 1.0
        self.inserts = 0
        self.evictions = 0
        self.stale_updates = 0

    def _init_layout(self, frag_len: int) -> None:
        self.frag_len = L = int(frag_len)
        self.num_slots = S = max(1, self.capacity // L)
        leaves = 1
        while leaves < S * L:
            leaves *= 2
        self._sum = SumSegmentTree(leaves)
        self._min = MinSegmentTree(leaves)
        self.slots = [None] * S
        self.slot_seq = np.zeros(S, np.int64)

    @property
    def mass(self) -> float:
        return self._sum.reduce() if self._sum is not None else 0.0

    @property
    def p_min(self) -> float:
        return self._min.reduce() if self._min is not None else float("inf")

    def insert_fragment(self, payload: Any, n: int, version: int = 0,
                        priorities: Optional[np.ndarray] = None) -> Any:
        """Index one fragment at the ring cursor.  Returns the evicted
        slot's payload token (None when the ring isn't full yet) so the
        caller can release it — in the distributed shard that drop IS
        the object-store eviction."""
        n = int(n)
        if self.frag_len is None:
            self._init_layout(n)
        if n != self.frag_len:
            raise ValueError(
                f"fragment of {n} rows in a shard laid out for "
                f"fixed-shape fragments of {self.frag_len} — the plane "
                "requires one fragment shape per buffer")
        slot = self.cursor
        evicted = self.slots[slot]
        self.slots[slot] = {"payload": payload, "version": int(version),
                            "n": n}
        self.slot_seq[slot] += 1
        if priorities is None:
            p = np.full(n, self.max_priority, np.float64)
        else:
            p = np.maximum(np.asarray(priorities, np.float64), self.eps)
            if p.shape != (n,):
                raise ValueError(f"priorities shape {p.shape} != ({n},)")
            self.max_priority = max(self.max_priority, float(p.max()))
        pa = p ** self.alpha
        base = slot * self.frag_len
        leaf_idx = np.arange(base, base + n, dtype=np.int64)
        self._sum.set_many(leaf_idx, pa)
        self._min.set_many(leaf_idx, pa)
        if evicted is None:
            self.size += n
        else:
            self.evictions += 1
        self.cursor = (slot + 1) % self.num_slots
        self.inserts += 1
        return None if evicted is None else evicted["payload"]

    def sample_rows(self, k: int,
                    uniforms: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Draw ``k`` rows proportional to priority mass (one vectorized
        prefix-sum descent).  Returns per-row slot/offset/leaf/seq/p/
        version arrays plus the payload token of every touched slot —
        the shape both the local plane and the shard actor reply with."""
        total = self.mass
        if k <= 0 or total <= 0.0 or self.size == 0:
            z = np.zeros(0, np.int64)
            return {"slot": z, "offset": z, "leaf": z, "seq": z,
                    "p": np.zeros(0, np.float64), "version": z,
                    "total": total, "p_min": self.p_min, "size": self.size,
                    "payloads": {}}
        u = self.rng.random(k) if uniforms is None else \
            np.asarray(uniforms, np.float64)
        leaves = self._sum.find_prefixsum_idx_many(u * total)
        pa = self._sum.value_many(leaves)
        bad = (pa <= 0.0) | (leaves >= self.num_slots * self.frag_len)
        if bad.any():
            # Float boundary landed in a zero-width (unoccupied) leaf:
            # re-route those lanes uniformly over the occupied prefix.
            leaves[bad] = self.rng.integers(0, self.size, int(bad.sum()))
            pa = self._sum.value_many(leaves)
        slot = leaves // self.frag_len
        offset = leaves % self.frag_len
        versions = np.array([self.slots[int(s)]["version"] for s in slot],
                            np.int64)
        uniq = np.unique(slot)
        payloads = {int(s): self.slots[int(s)]["payload"] for s in uniq}
        return {"slot": slot, "offset": offset, "leaf": leaves,
                "seq": self.slot_seq[slot].copy(), "p": pa,
                "version": versions, "total": total, "p_min": self.p_min,
                "size": self.size, "payloads": payloads}

    def update_priorities(self, leaves: np.ndarray, seqs: np.ndarray,
                          priorities: np.ndarray) -> int:
        """Batched priority write; rows whose slot was re-used since the
        sample (sequence mismatch) are dropped — late updates are
        expected under async flow, not an error.  Returns applied count."""
        if self._sum is None:
            return 0
        leaves = np.asarray(leaves, np.int64)
        seqs = np.asarray(seqs, np.int64)
        p = np.asarray(priorities, np.float64)
        ok = self.slot_seq[leaves // self.frag_len] == seqs
        self.stale_updates += int((~ok).sum())
        if not ok.any():
            return 0
        p = np.maximum(p[ok], self.eps)
        pa = p ** self.alpha
        self._sum.set_many(leaves[ok], pa)
        self._min.set_many(leaves[ok], pa)
        self.max_priority = max(self.max_priority, float(p.max()))
        return int(ok.sum())

    def stats(self) -> Dict[str, Any]:
        return {
            "size": self.size,
            "capacity": self.capacity,
            "fill": self.size / self.capacity if self.capacity else 0.0,
            "mass": self.mass,
            "inserts": self.inserts,
            "evictions": self.evictions,
            "stale_updates": self.stale_updates,
            "max_priority": self.max_priority,
        }


# ---------------------------------------------------------------------------
# ReplayShard: the thin actor over ShardCore
# ---------------------------------------------------------------------------

@ray_tpu.remote
class ReplayShard:
    """Thin actor wrapper: indexes fragment REFS (pinning them via the
    borrower protocol) and answers priority-ordered draws.  Payload bytes
    never enter this process — insert is ref bookkeeping, eviction drops
    the evicted slot's refs so the store reclaims the segments."""

    def __init__(self, capacity: int, alpha: float = 0.0, seed: int = 0,
                 shard_index: int = 0):
        self.core = ShardCore(capacity, alpha=alpha, seed=seed)
        self.shard_index = int(shard_index)

    def ping(self):
        return "ok"

    def pid(self):
        import os

        return os.getpid()

    def insert(self, refs: Dict[str, Any], n: int, version: int = 0,
               priorities=None) -> Dict[str, Any]:
        # The evicted {col: ref} dict goes out of scope right here — the
        # deserialized ObjectRefs' finalizers release this process's
        # borrows, which IS the eviction.
        evicted = self.core.insert_fragment(refs, n, version, priorities)
        if evicted is not None:
            del evicted
            # Push the deferred ref releases out now instead of at the
            # gc thread's next wakeup: eviction should return segments
            # to the store pool before the NEXT insert's bytes arrive
            # (bounded store residency; run_replay_smoke pins this).
            from ray_tpu._private.worker import global_worker

            try:
                global_worker._drain_ref_gc_queue()
            except Exception:
                pass
        return {"mass": self.core.mass, "size": self.core.size,
                "p_min": self.core.p_min}

    def sample(self, k: int) -> Dict[str, Any]:
        return self.core.sample_rows(int(k))

    def update_priorities(self, leaves, seqs, priorities) -> int:
        return self.core.update_priorities(leaves, seqs, priorities)

    def stats(self) -> Dict[str, Any]:
        out = self.core.stats()
        out["shard"] = self.shard_index
        return out


class _ShardSetConfig:
    """Minimal config shim so shards ride WorkerSet's strike/replacement
    machinery (the only field WorkerSet reads with a factory)."""

    def __init__(self, n: int):
        self.num_rollout_workers = n


# ---------------------------------------------------------------------------
# ReplayBatch
# ---------------------------------------------------------------------------

class ReplayBatch:
    """One assembled ``[B, ...]`` learner batch.

    ``data`` maps column name -> np.ndarray; ``weights`` are the
    importance-sampling weights (all-ones in uniform mode; zeroed for
    rows failing the staleness gate); ``ids`` is ``[B, 3]`` int64
    ``(shard, leaf, seq)`` — the opaque handle update_priorities takes;
    ``versions`` are the per-row weight-version stamps."""

    __slots__ = ("data", "weights", "ids", "versions")

    def __init__(self, data, weights, ids, versions):
        self.data = data
        self.weights = weights
        self.ids = ids
        self.versions = versions

    def __getitem__(self, key: str) -> np.ndarray:
        return self.data[key]

    def __contains__(self, key: str) -> bool:
        return key in self.data

    def __len__(self) -> int:
        return len(self.weights)


def _plane_metrics():
    """Lazy replay_* metric handles (internal_kv needs a live driver)."""
    from ray_tpu.util.metrics import Gauge, Histogram, Meter

    return {
        "inserts": Meter("replay_inserts_total",
                         "fragments indexed by the replay plane"),
        "insert_rows": Meter("replay_insert_rows_total",
                             "transitions indexed by the replay plane"),
        "samples": Meter("replay_samples_total",
                         "batches sampled from the replay plane"),
        "sample_rows": Meter("replay_sample_rows_total",
                             "transitions sampled from the replay plane"),
        "stale_rows": Meter("replay_stale_rows_total",
                            "sampled rows masked by the staleness gate"),
        "fill": Gauge("replay_shard_fill",
                      "per-shard fill fraction", tag_keys=("shard",)),
        "mass": Gauge("replay_shard_priority_mass",
                      "per-shard total priority mass",
                      tag_keys=("shard",)),
        "upd_lag": Histogram(
            "replay_priority_update_lag_s",
            "enqueue-to-apply lag of async priority updates",
            boundaries=(0.001, 0.01, 0.1, 1.0, 10.0)),
    }


# ---------------------------------------------------------------------------
# ReplayPlane
# ---------------------------------------------------------------------------

class ReplayPlane:
    """User-facing replay handle — local single-shard or sharded on the
    object plane.  See the module docstring for the architecture."""

    def __init__(self, capacity: int, num_shards: int = 0,
                 alpha: float = 0.0, beta: float = 0.4, seed: int = 0,
                 n_step: int = 1, gamma: float = 0.99,
                 max_weight_staleness: Optional[int] = None,
                 insert_window: int = 4, update_depth: int = 4,
                 eps: float = 1e-6):
        self.capacity = int(capacity)
        self.num_shards = int(num_shards)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.n_step = int(n_step)
        self.gamma = float(gamma)
        self.max_weight_staleness = max_weight_staleness
        self._learner_version: Optional[int] = None
        self._np_rng = np.random.default_rng(seed)
        self._lock = threading.RLock()
        self._metrics = None
        self._metrics_dead = False
        self.gather_calls = 0          # batched get_many gathers issued
        self.sample_stamps: List[Tuple[float, float]] = []  # (t0, t1)
        self.stale_rows = 0
        self._closed = False

        self._core: Optional[ShardCore] = None
        self._shard_set = None
        self._insert_windows: List[Window] = []
        self._route_i = 0
        self._masses: Optional[np.ndarray] = None
        self._sizes: Optional[np.ndarray] = None
        self._p_mins: Optional[np.ndarray] = None
        self._upd_q: Optional[_queue.Queue] = None
        self._upd_stage: Optional[Stage] = None
        self._upd_token: Optional[CancellationToken] = None

        if self.num_shards <= 0:
            self._core = ShardCore(capacity, alpha=alpha, seed=seed,
                                   eps=eps)
        else:
            from ray_tpu.rllib.evaluation.worker_set import WorkerSet

            per_shard = max(1, self.capacity // self.num_shards)

            def factory(i):
                return ReplayShard.options(max_restarts=1).remote(
                    per_shard, alpha, seed + 7919 * i, i)

            self._shard_set = WorkerSet(_ShardSetConfig(self.num_shards),
                                        None, worker_factory=factory)
            self._insert_windows = [Window(max(1, insert_window))
                                    for _ in range(self.num_shards)]
            self._masses = np.zeros(self.num_shards)
            self._sizes = np.zeros(self.num_shards, np.int64)
            self._p_mins = np.full(self.num_shards, np.inf)
            self._upd_q = _queue.Queue(maxsize=max(1, update_depth))

    # ---- mode / config plumbing -----------------------------------------
    @classmethod
    def from_config(cls, cfg, seed: Optional[int] = None) -> "ReplayPlane":
        """Build from an AlgorithmConfig's replay knobs (getattr-guarded
        so older config objects keep working)."""
        prioritized = bool(getattr(cfg, "replay_prioritized", False))
        return cls(
            capacity=getattr(cfg, "buffer_size", 50_000),
            num_shards=int(getattr(cfg, "replay_num_shards", 0)),
            alpha=(float(getattr(cfg, "replay_alpha", 0.6))
                   if prioritized else 0.0),
            beta=float(getattr(cfg, "replay_beta", 0.4)),
            seed=int(seed if seed is not None else getattr(cfg, "seed", 0)),
            n_step=int(getattr(cfg, "n_step", 1)),
            gamma=float(getattr(cfg, "gamma", 0.99)),
            max_weight_staleness=getattr(cfg, "replay_max_weight_staleness",
                                         None),
        )

    @property
    def distributed(self) -> bool:
        return self._shard_set is not None

    @property
    def size(self) -> int:
        if self._core is not None:
            return self._core.size
        with self._lock:
            self._sync_inserts()
            return int(self._sizes.sum())

    @property
    def mass(self) -> float:
        if self._core is not None:
            return self._core.mass
        with self._lock:
            self._sync_inserts()
            return float(self._masses.sum())

    def note_weights_version(self, version: int) -> None:
        """Record the learner's current weights version — the reference
        point for the max_weight_staleness gate on sampled rows."""
        self._learner_version = int(version)

    # ---- metrics ---------------------------------------------------------
    def _m(self):
        if self._metrics_dead:
            return None
        if self._metrics is None:
            try:
                self._metrics = _plane_metrics()
            except Exception:
                self._metrics_dead = True
        return self._metrics

    def _mark(self, key: str, value: float = 1.0) -> None:
        m = self._m()
        if m is None:
            return
        try:
            m[key].mark(value)
        except Exception:
            self._metrics_dead = True

    def _export_shard_gauges(self, i: int, size: int, mass: float) -> None:
        m = self._m()
        if m is None:
            return
        try:
            per = (self.capacity // self.num_shards
                   if self.distributed else self.capacity) or 1
            tags = {"shard": str(i)}
            m["fill"].set(min(1.0, size / per), tags)
            m["mass"].set(float(mass), tags)
        except Exception:
            self._metrics_dead = True

    def flush_metrics(self) -> None:
        """Force pending Meter marks into the KV (tests / shutdown)."""
        m = self._m()
        if m is None:
            return
        for h in m.values():
            if hasattr(h, "flush"):
                try:
                    h.flush()
                except Exception:
                    pass
        if self._core is not None:
            self._export_shard_gauges(0, self._core.size, self._core.mass)

    # ---- insert ----------------------------------------------------------
    def insert(self, batch: Dict[str, np.ndarray],
               priorities: Optional[np.ndarray] = None, version: int = 0,
               num_envs: Optional[int] = None) -> None:
        """Index one rollout fragment.  Local mode keeps the column dict
        as the payload (no copy); distributed mode publishes the columns
        with ONE ``put_many`` burst and ships the refs to a shard.
        ``num_envs`` gives the row layout for n-step folding."""
        if self.n_step > 1:
            batch = compute_nstep(batch, num_envs or 1, self.gamma,
                                  self.n_step)
        n = len(batch["rewards"])
        if self._core is not None:
            with self._lock:
                self._core.insert_fragment(dict(batch), n, version,
                                           priorities)
                self._export_shard_gauges(0, self._core.size,
                                          self._core.mass)
        else:
            cols = sorted(batch)
            refs = ray_tpu.put_many([np.ascontiguousarray(batch[c])
                                     for c in cols])
            self.insert_refs(dict(zip(cols, refs)), n, version, priorities)
        self._mark("inserts")
        self._mark("insert_rows", n)

    def insert_refs(self, refs: Dict[str, Any], n: int, version: int = 0,
                    priorities: Optional[np.ndarray] = None) -> None:
        """Distributed insert: route a published fragment's refs to a
        shard (round-robin over live shards), bounded in flight per
        shard by a flow.Window of un-harvested acks."""
        if not self.distributed:
            raise RuntimeError("insert_refs needs a sharded plane")
        with self._lock:
            i = self._route_i % self.num_shards
            self._route_i += 1
            shard = self._shard_set.workers[i]
            fut = shard.insert.remote(refs, int(n), int(version), priorities)
            win = self._insert_windows[i]
            # Hold the refs alongside the ack future: the fragment objects
            # are owner-resident in THIS process, and dropping our local
            # refs before the shard's borrow registration lands would let
            # ref-gc free them mid-flight (the make_args large-arg race).
            # The ack proves the shard holds its borrows; then we release.
            win.append((fut, refs))
            while win.over_depth:
                f, _held = win.popleft()
                self._harvest_insert_ack(i, f, block=True)
            self._mark("inserts")
            self._mark("insert_rows", n)

    def _harvest_insert_ack(self, i: int, fut, block: bool) -> None:
        try:
            ack = ray_tpu.get(fut, timeout=60.0 if block else 0.0)
        except ray_tpu.exceptions.RayTpuError:
            self._on_shard_failure(i)
            return
        self._masses[i] = ack["mass"]
        self._sizes[i] = ack["size"]
        self._p_mins[i] = ack["p_min"]
        self._export_shard_gauges(i, ack["size"], ack["mass"])

    def _drain_insert_acks(self) -> None:
        """Poll-harvest landed insert acks (refreshes the shard mass
        snapshot sampling draws from) without blocking."""
        for i, win in enumerate(self._insert_windows):
            while win:
                fut, _held = win.peek()
                try:
                    ready, _ = ray_tpu.wait([fut], num_returns=1,
                                            timeout=0.0)
                except ray_tpu.exceptions.RayTpuError:
                    win.popleft()
                    self._on_shard_failure(i)
                    continue
                if not ready:
                    break
                win.popleft()
                self._harvest_insert_ack(i, fut, block=True)

    def _sync_inserts(self) -> None:
        """Block-harvest every pending insert ack: the authoritative
        size/mass barrier (and the point held fragment refs release)."""
        for i, win in enumerate(self._insert_windows):
            while win:
                fut, _held = win.popleft()
                self._harvest_insert_ack(i, fut, block=True)

    def _on_shard_failure(self, i: int) -> None:
        """One strike via the WorkerSet machinery; a struck-out shard is
        replaced by a fresh (empty) one and its mass leaves the draw."""
        replaced = self._shard_set.report_failure_index(i)
        if replaced:
            self._masses[i] = 0.0
            self._sizes[i] = 0
            self._p_mins[i] = np.inf
            self._insert_windows[i].clear()
            self._export_shard_gauges(i, 0, 0.0)

    # ---- sampling --------------------------------------------------------
    def sample(self, batch_size: int, beta: Optional[float] = None,
               rng: Optional[np.random.Generator] = None) -> ReplayBatch:
        """One ``[B, ...]`` batch: two-level priority draw resolved with
        ONE batched get_many gather (distributed) or direct views
        (local)."""
        t0 = time.monotonic()
        beta = self.beta if beta is None else float(beta)
        if self._core is not None:
            with self._lock:
                k = int(batch_size)
                u = rng.random(k) if rng is not None else None
                rows = self._core.sample_rows(k, uniforms=u)
                parts = [(0, rows)]
                resolved = {(0, s): p for s, p in rows["payloads"].items()}
                totals = {0: rows["total"]}
                sizes = {0: rows["size"]}
                p_mins = {0: rows["p_min"]}
                batch = self._assemble(parts, resolved, totals, sizes,
                                       p_mins, beta, int(batch_size), rng)
        else:
            batch = self._sample_distributed(int(batch_size), beta, rng)
        t1 = time.monotonic()
        self.sample_stamps.append((t0, t1))
        if len(self.sample_stamps) > 256:
            del self.sample_stamps[:128]
        self._mark("samples")
        self._mark("sample_rows", len(batch))
        return batch

    def _sample_distributed(self, B: int, beta: float,
                            rng: Optional[np.random.Generator]
                            ) -> ReplayBatch:
        gen = rng if rng is not None else self._np_rng
        with self._lock:
            self._drain_insert_acks()
            parts: List[Tuple[int, Dict[str, Any]]] = []
            got = 0
            # Retry rounds: a dead shard's draw mass re-spreads over the
            # survivors so the learner still gets a full batch.
            for _round in range(max(2, self.num_shards + 1)):
                need = B - got
                if need <= 0:
                    break
                masses = np.maximum(self._masses, 0.0)
                total = masses.sum()
                if total <= 0.0:
                    self._refresh_stats()
                    masses = np.maximum(self._masses, 0.0)
                    total = masses.sum()
                    if total <= 0.0:
                        break
                counts = gen.multinomial(need, masses / total)
                futures = [(i, self._shard_set.workers[i].sample.remote(
                    int(c))) for i, c in enumerate(counts) if c > 0]
                for i, fut in futures:
                    try:
                        reply = ray_tpu.get(fut, timeout=60.0)
                    except ray_tpu.exceptions.RayTpuError:
                        self._on_shard_failure(i)
                        continue
                    self._masses[i] = reply["total"]
                    self._sizes[i] = reply["size"]
                    self._p_mins[i] = reply["p_min"]
                    k = len(reply["slot"])
                    if k:
                        parts.append((i, reply))
                        got += k
            if got == 0:
                raise RuntimeError(
                    "replay plane could not sample: no live shard holds "
                    "data (all shards empty or dead)")
            # ONE batched gather for every sampled fragment column.
            resolved: Dict[Tuple[int, int], Dict[str, np.ndarray]] = {}
            flat_refs: List[Any] = []
            flat_keys: List[Tuple[int, int, str]] = []
            seen = set()
            for i, reply in parts:
                for s, refdict in reply["payloads"].items():
                    if (i, s) in seen:
                        continue
                    seen.add((i, s))
                    for col, ref in refdict.items():
                        flat_refs.append(ref)
                        flat_keys.append((i, int(s), col))
            values = ray_tpu.get_many(flat_refs)
            self.gather_calls += 1
            for (i, s, col), v in zip(flat_keys, values):
                resolved.setdefault((i, s), {})[col] = v
            totals = {i: float(self._masses[i]) for i in
                      range(self.num_shards)}
            sizes = {i: int(self._sizes[i]) for i in range(self.num_shards)}
            p_mins = {i: float(self._p_mins[i]) for i in
                      range(self.num_shards)}
            return self._assemble(parts, resolved, totals, sizes, p_mins,
                                  beta, B, rng)

    def _refresh_stats(self) -> None:
        futures = [(i, w.stats.remote())
                   for i, w in enumerate(self._shard_set.workers)]
        for i, fut in futures:
            try:
                st = ray_tpu.get(fut, timeout=30.0)
            except ray_tpu.exceptions.RayTpuError:
                self._on_shard_failure(i)
                continue
            self._masses[i] = st["mass"]
            self._sizes[i] = st["size"]

    def _assemble(self, parts, resolved, totals, sizes, p_mins, beta, B,
                  rng) -> ReplayBatch:
        """Fuse shard replies + resolved payload columns into one
        compile-once [B, ...] batch (fixed B: short draws — possible only
        after shard loss — pad by resampling assembled rows)."""
        got = sum(len(reply["slot"]) for _i, reply in parts)
        first_payload = next(iter(resolved.values()))
        col_names = [c for c in first_payload if c != "actions_logp"]
        data = {}
        for col in col_names:
            proto = first_payload[col]
            data[col] = np.empty((got,) + proto.shape[1:], proto.dtype)
        ids = np.empty((got, 3), np.int64)
        versions = np.empty(got, np.int64)
        p_all = np.empty(got, np.float64)
        cursor = 0
        for i, reply in parts:
            k = len(reply["slot"])
            sl = slice(cursor, cursor + k)
            slots, offs = reply["slot"], reply["offset"]
            for s in np.unique(slots):
                m = slots == s
                arrs = resolved[(i, int(s))]
                for col in col_names:
                    data[col][sl][m] = arrs[col][offs[m]]
            ids[sl, 0] = i
            ids[sl, 1] = reply["leaf"]
            ids[sl, 2] = reply["seq"]
            versions[sl] = reply["version"]
            p_all[sl] = reply["p"]
            cursor += k
        # IS weights from GLOBAL mass/size/min (uniform mode: all ones).
        total = sum(t for t in totals.values() if np.isfinite(t))
        n_total = sum(sizes.values())
        finite_mins = [v for v in p_mins.values() if np.isfinite(v)]
        if self.alpha == 0.0 or total <= 0.0 or not finite_mins:
            weights = np.ones(got, np.float32)
        else:
            p_min = min(finite_mins)
            max_w = (max(p_min, 1e-12) / total * max(n_total, 1)) ** (-beta)
            weights = ((p_all / total * max(n_total, 1)) ** (-beta)
                       / max_w).astype(np.float32)
        if got < B:
            pad_rng = rng if rng is not None else self._np_rng
            pad = pad_rng.integers(0, got, B - got)
            for col in col_names:
                data[col] = np.concatenate([data[col], data[col][pad]])
            ids = np.concatenate([ids, ids[pad]])
            versions = np.concatenate([versions, versions[pad]])
            weights = np.concatenate([weights, weights[pad]])
        if self.max_weight_staleness is not None and \
                self._learner_version is not None:
            lag = self._learner_version - versions
            stale = lag > self.max_weight_staleness
            n_stale = int(stale.sum())
            if n_stale:
                weights = np.where(stale, 0.0, weights).astype(np.float32)
                self.stale_rows += n_stale
                self._mark("stale_rows", n_stale)
        return ReplayBatch(data, weights, ids, versions)

    def sample_stacked(self, rng, num_batches: int, batch_size: int):
        """[U, B, ...] stacked learner minibatches as device arrays — the
        HostReplay-compatible shape one jax device round trip feeds into
        a lax.scan of updates.  ``rng`` (np Generator) drives the draws
        so determinism still flows from the algorithm seed."""
        import jax.numpy as jnp

        batches = [self.sample(batch_size, rng=rng)
                   for _ in range(num_batches)]
        cols = [c for c in LEARNER_COLS if c in batches[0].data]
        return {c: jnp.asarray(np.stack([b[c] for b in batches]))
                for c in cols}

    def prefetch(self, batch_size: int, beta: Optional[float] = None,
                 depth: int = 2) -> Stage:
        """flow.Stage keeping up to ``depth`` gathered batches in flight:
        the gather + host assembly of batch i+1 overlaps the learner's
        SGD on batch i.  Iterate it for batches; ``close()`` to drain."""
        import itertools

        return Stage(itertools.count(),
                     lambda _i: self.sample(batch_size, beta),
                     depth=max(1, depth), workers=1,
                     name="replay_gather")

    # ---- priority updates ------------------------------------------------
    def update_priorities(self, ids: np.ndarray,
                          priorities: np.ndarray) -> None:
        """Feed TD-error priorities back.  Local: direct vectorized
        write.  Distributed: enqueue on the bounded flow.Stage sink —
        pending batches coalesce into one RPC per shard per send, and a
        full queue backpressures the learner."""
        ids = np.asarray(ids, np.int64)
        priorities = np.asarray(priorities, np.float64)
        if ids.size == 0:
            return
        if self._core is not None:
            with self._lock:
                self._core.update_priorities(ids[:, 1], ids[:, 2],
                                             priorities)
            return
        self._ensure_update_stage()
        self._upd_q.put((ids, priorities, time.monotonic()))

    def _ensure_update_stage(self) -> None:
        if self._upd_stage is not None:
            return
        with self._lock:
            if self._upd_stage is not None:
                return
            self._upd_token = CancellationToken()
            q, token = self._upd_q, self._upd_token

            def source():
                while not token.cancelled:
                    try:
                        item = q.get(timeout=0.2)
                    except _queue.Empty:
                        continue
                    if item is _CLOSE:
                        return
                    yield item

            self._upd_stage = Stage(source(), self._send_priority_updates,
                                    depth=1, workers=1, sink=True,
                                    name="replay_prio",
                                    token=self._upd_token)

    def _send_priority_updates(self, first) -> None:
        """Sink fn: coalesce everything queued behind ``first`` into one
        update RPC per shard; harvest acks with strike handling."""
        items = [first]
        while True:
            try:
                nxt = self._upd_q.get_nowait()
            except _queue.Empty:
                break
            if nxt is _CLOSE:
                break
            items.append(nxt)
        ids = np.concatenate([it[0] for it in items])
        prios = np.concatenate([it[1] for it in items])
        oldest = min(it[2] for it in items)
        futures = []
        for i in np.unique(ids[:, 0]):
            m = ids[:, 0] == i
            shard = self._shard_set.workers[int(i)]
            futures.append((int(i), shard.update_priorities.remote(
                ids[m, 1], ids[m, 2], prios[m])))
        for i, fut in futures:
            try:
                ray_tpu.get(fut, timeout=30.0)
            except ray_tpu.exceptions.RayTpuError:
                self._on_shard_failure(i)
        m = self._m()
        if m is not None:
            try:
                m["upd_lag"].observe(time.monotonic() - oldest)
            except Exception:
                self._metrics_dead = True

    # ---- lifecycle / observability --------------------------------------
    def stats(self) -> Dict[str, Any]:
        if self._core is not None:
            out = self._core.stats()
            out.update(num_shards=0, gather_calls=self.gather_calls,
                       stale_rows=self.stale_rows)
            return out
        return {
            "num_shards": self.num_shards,
            "size": self.size,
            "mass": self.mass,
            "per_shard_size": [int(s) for s in self._sizes],
            "per_shard_mass": [float(m) for m in self._masses],
            "gather_calls": self.gather_calls,
            "stale_rows": self.stale_rows,
            "num_healthy_shards": self._shard_set.num_healthy_workers,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._upd_stage is not None:
            try:
                self._upd_q.put_nowait(_CLOSE)
            except _queue.Full:
                pass
            self._upd_stage.close()
            self._upd_stage = None
        self.flush_metrics()
        if self._shard_set is not None:
            for win in self._insert_windows:
                win.clear()
            self._shard_set.stop()
            self._shard_set = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# The shared actor-topology iteration for the replay family (DQN/SAC/TD3)
# ---------------------------------------------------------------------------

def run_actor_replay_iter(algo, explore_arg, batch_size, do_updates):
    """ONE shared actor-topology iteration for the replay family
    (DQN/SAC/TD3): harvest transitions from the rollout actors into the
    algorithm's :class:`ReplayPlane`, run the algorithm's updates once
    warm, and assemble the common metrics (reward EMA, worker health).

    Local plane (``replay_num_shards=0``): workers ship raw batches and
    the plane indexes them in-process (the historical HostReplay path,
    one implementation instead of three).  Sharded plane: workers
    ``sample_publish`` fragment refs — bytes go rollout worker -> object
    store -> learner gather, never through the insert path."""
    import jax
    import numpy as np

    cfg = algo.config
    plane: ReplayPlane = algo._rb
    metrics: Dict[str, Any] = {}
    steps_this_iter = 0
    if plane.distributed:
        results = algo.workers.publish_sync(explore_arg, cfg.gamma,
                                            plane.n_step)
        returns: List[float] = []
        for refs, meta, completed in results:
            plane.insert_refs(refs, meta["n"],
                              version=meta.get("version", 0))
            steps_this_iter += int(meta["n"])
            returns.extend(completed)
        algo._env_steps += steps_this_iter
    else:
        batches, returns = algo.workers.sample_sync(explore_arg)
        for b in batches:
            plane.insert(b, version=algo.workers.weights_version,
                         num_envs=cfg.num_envs_per_worker)
            n = len(b["rewards"])
            algo._env_steps += n
            steps_this_iter += n
    metrics["replay_size"] = plane.size
    if returns:
        mean_r = float(np.mean(returns))
        prev = getattr(algo, "_ep_reward_ema", None)
        algo._ep_reward_ema = (mean_r if prev is None
                               else 0.7 * prev + 0.3 * mean_r)
        metrics["episodes_this_iter"] = len(returns)
    if getattr(algo, "_ep_reward_ema", None) is not None:
        metrics["episode_reward_mean"] = algo._ep_reward_ema
    if plane.size >= cfg.learning_starts:
        # Algorithms may pin an actor-mode update count (e.g. DQN's
        # replay-ratio-derived default) — num_updates_per_iter's default
        # is tuned for the anakin path's huge batches.
        U = getattr(algo, "_actor_updates", None) or cfg.num_updates_per_iter
        stacked = plane.sample_stacked(algo._host_rng, U, batch_size)
        keys = jax.random.split(jax.random.PRNGKey(algo._env_steps), U)
        metrics.update(do_updates(stacked, keys))
        version = algo.workers.sync_weights(
            jax.device_get(algo._sync_params()))
        plane.note_weights_version(version)
    metrics["num_env_steps_sampled_this_iter"] = steps_this_iter
    metrics["num_healthy_workers"] = algo.workers.num_healthy_workers
    return metrics
