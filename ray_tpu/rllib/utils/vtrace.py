"""V-trace off-policy correction (IMPALA), jax scan implementation.

Reference: rllib/algorithms/impala/vtrace_torch.py — re-derived from the
IMPALA paper's recursion, not translated:
    vs = V(xs) + sum_t gamma^t * (prod c) * rho_t * delta_t
computed right-to-left with clipped importance weights.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def vtrace(behaviour_logp, target_logp, rewards, values, dones, last_value,
           gamma: float = 0.99, clip_rho: float = 1.0, clip_c: float = 1.0):
    """All inputs time-major [T, N]; last_value [N].

    Returns (vs, pg_advantages): value targets for the critic and
    importance-corrected advantages for the policy gradient."""
    rhos = jnp.exp(target_logp - behaviour_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    clipped_cs = jnp.minimum(clip_c, rhos)
    nonterminal = 1.0 - dones.astype(jnp.float32)

    values_tp1 = jnp.concatenate([values[1:], last_value[None]], axis=0)
    deltas = clipped_rhos * (
        rewards + gamma * values_tp1 * nonterminal - values)

    def step(acc, xs):
        delta, c, nt = xs
        acc = delta + gamma * nt * c * acc
        return acc, acc

    _, vs_minus_v_rev = jax.lax.scan(
        step, jnp.zeros_like(last_value),
        (deltas[::-1], clipped_cs[::-1], nonterminal[::-1]))
    vs_minus_v = vs_minus_v_rev[::-1]
    vs = values + vs_minus_v

    vs_tp1 = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    pg_adv = clipped_rhos * (rewards + gamma * vs_tp1 * nonterminal - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)
