"""Data-parallel mesh plumbing for the anakin train step.

Reference shape: the learner-group DDP fan-out in
rllib/core/rl_trainer/trainer_runner.py:75-90 and the multi-GPU tower
loop in rllib/execution/train_ops.py:82 — one replica per device, grads
all-reduced.  TPU-first redesign: there are no towers and no NCCL
buckets; the whole train step (env rollout + GAE + SGD) is ONE SPMD
program `shard_map`-ed over a `data` mesh axis.  Envs live sharded on
the axis, parameters are replicated, and the only communication is a
`psum`/`pmean` over gradients (and episode counters) that XLA lowers to
an ICI all-reduce.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"


def data_mesh(num_devices: int) -> Mesh:
    """A 1-D `data` mesh over the first `num_devices` local devices."""
    devs = jax.devices()
    if num_devices > len(devs):
        raise ValueError(
            f"num_devices={num_devices} but only {len(devs)} jax devices "
            "are visible (set XLA_FLAGS=--xla_force_host_platform_device_"
            "count=N for a virtual CPU mesh)")
    return Mesh(np.asarray(devs[:num_devices]), (DATA_AXIS,))


def pmean_if(x, sharded: bool):
    return jax.lax.pmean(x, DATA_AXIS) if sharded else x


def psum_if(x, sharded: bool):
    return jax.lax.psum(x, DATA_AXIS) if sharded else x


def normalize_global(x, sharded: bool, eps: float = 1e-8):
    """Mean/std normalization over the GLOBAL batch: local moments are
    pmean'd across the data axis so the sharded update matches the
    single-device one at equal global batch."""
    import jax.numpy as jnp

    m = pmean_if(x.mean(), sharded)
    var = pmean_if(jnp.mean((x - m) ** 2), sharded)
    return (x - m) / (jnp.sqrt(var) + eps)


def state_sharding(mesh: Mesh, state_specs):
    """Pytree-prefix of NamedShardings matching a pytree-prefix of
    PartitionSpecs (for jit out_shardings on the init fn)."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s), state_specs,
                        is_leaf=lambda s: isinstance(s, P))


def _shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """Version shim: jax >= 0.6 exposes top-level ``jax.shard_map`` with
    ``check_vma``; older jax (this image ships 0.4.x) has
    ``jax.experimental.shard_map.shard_map`` with the same knob under its
    pre-rename name ``check_rep``."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except AttributeError:
            pass  # deprecation stub that raises on access (jax 0.4.3x)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def shard_train_step(step_fn, mesh: Mesh, state_specs, donate: bool = False):
    """jit(shard_map(...)) for a `state -> (state, metrics)` train step.

    `state_specs` is a pytree prefix of PartitionSpecs for the state;
    metrics are replicated (the step body must pmean/psum them)."""
    mapped = _shard_map(step_fn, mesh=mesh, in_specs=(state_specs,),
                        out_specs=(state_specs, P()))
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def zero_train_step(step_fn, mesh: Mesh, state_specs, donate: bool = False):
    """`shard_train_step` for a ZeRO-sharded state (drop-in; see
    ray_tpu.parallel.zero).

    Identical compilation contract — the difference is carried by
    `state_specs`: the optimizer-state subtree is the per-leaf spec pytree
    from `ZeroSharder.opt_specs` (``[world, chunk]`` leaves P(data),
    scalars replicated) instead of a blanket P(), so each replica's state
    block is 1/N and the step body's reduce-scatter/all-gather pair (built
    by `zero.make_update_fn`) is the only cross-replica traffic."""
    return shard_train_step(step_fn, mesh, state_specs, donate=donate)


def build_update_plan(config, lr, grad_clip, params_template, D, sharded):
    """The gradient-application recipe every anakin algorithm shares,
    resolved from ``config.zero_sharding`` / ``config.quantized_collectives``
    — one copy so PPO and IMPALA cannot drift.

    Returns ``(update_fn, opt_init, opt_specs)``:
    ``update_fn(grads, opt_state, params) -> (params, opt_state)`` runs
    INSIDE the shard_map body (grads are the local, un-reduced values);
    ``opt_init(params)`` builds the (possibly globally sharded) optimizer
    state; ``opt_specs`` is its PartitionSpec pytree (a bare ``P()`` on the
    replicated paths).

    - default: ``pmean`` grads + replicated optax update (today's math),
    - ``quantized_collectives=int8``: the block-scaled int8 all-reduce
      from ``ray_tpu.ops.collectives`` in place of the fp32 pmean,
    - ``zero_sharding=opt|opt+grads``: the ZeRO plane from
      ``ray_tpu.parallel.zero`` — 1/N optimizer state per replica,
      reduce-scattered grads, all-gathered fresh params (grad_clip maps
      to ``zero_clip_by_global_norm`` so the clip stays exactly global).

    Both knobs require the SPMD path: without ``num_devices`` there is no
    mesh axis to shard or quantize over, and silently ignoring the
    request is the worst failure — so it raises."""
    import optax

    zero_mode = getattr(config, "zero_sharding", "off") or "off"
    quant = getattr(config, "quantized_collectives", "off") or "off"
    if zero_mode not in ("off", "opt", "opt+grads"):
        raise ValueError(f"zero_sharding must be off|opt|opt+grads, "
                         f"got {zero_mode!r}")
    if quant not in ("off", "int8"):
        raise ValueError(f"quantized_collectives must be off|int8, "
                         f"got {quant!r}")
    if (zero_mode != "off" or quant != "off") and not sharded:
        raise ValueError(
            "zero_sharding/quantized_collectives require the SPMD path: "
            "set resources(num_devices=...) (1 is valid)")

    if zero_mode == "off":
        parts = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        tx = optax.chain(*parts, optax.adam(lr))
        if quant == "int8":
            from ray_tpu.ops import collectives

            def update_fn(grads, opt_state, params):
                grads = collectives.quantized_pmean(grads, DATA_AXIS, D)
                updates, opt_state = tx.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state
        else:
            def update_fn(grads, opt_state, params):
                grads = pmean_if(grads, sharded)
                updates, opt_state = tx.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state
        return update_fn, tx.init, P()

    from ray_tpu.parallel import zero as zero_mod

    zparts = [zero_mod.zero_clip_by_global_norm(grad_clip, DATA_AXIS)] \
        if grad_clip else []
    tx = optax.chain(*zparts, optax.adam(lr))
    zu = zero_mod.build_zero_update(params_template, tx, D,
                                    zero_sharding=zero_mode,
                                    quantized=quant, axis_name=DATA_AXIS)
    return zu.update, zu.init_opt, zu.opt_specs


def resolve_num_devices(config_num_devices: Optional[int]) -> Optional[int]:
    """None → legacy jit path; int → SPMD path.  Validates only; if the
    count exceeds the visible devices, data_mesh raises at build time."""
    if config_num_devices is None:
        return None
    n = int(config_num_devices)
    if n < 1:
        raise ValueError(f"num_devices must be >= 1, got {n}")
    return n


def setup_data_mesh(config, num_envs: int):
    """Shared anakin data-mesh wiring: returns (D, sharded, mesh) from
    ``config.num_devices``, enforcing env divisibility.  One copy so the
    divisibility error and mesh construction cannot drift between
    algorithms (PPO/IMPALA both call this)."""
    D = resolve_num_devices(getattr(config, "num_devices", None))
    if D is None:
        return None, False, None
    if num_envs % D:
        raise ValueError(f"num_envs={num_envs} not divisible by "
                         f"num_devices={D}")
    return D, True, data_mesh(D)


def reject_data_mesh(config, path: str) -> None:
    """Paths that have no shard_map implementation must refuse a
    num_devices request loudly — silently running single-device while the
    user believes they are N-way data-parallel is the worst failure."""
    if getattr(config, "num_devices", None) is not None:
        raise NotImplementedError(
            f"resources(num_devices=...) is not implemented for {path}; "
            "the data-parallel anakin step currently covers feedforward "
            "PPO and IMPALA/APPO")
    if getattr(config, "zero_sharding", "off") != "off" or \
            getattr(config, "quantized_collectives", "off") != "off":
        raise NotImplementedError(
            f"zero_sharding/quantized_collectives are not implemented for "
            f"{path}; they ride the shard_map data-parallel step")


def split_rng(rng, D: Optional[int], sharded: bool):
    """State rng leaf: per-device key rows [D, 2] when sharded."""
    import jax

    return jax.random.split(rng, D) if sharded else rng


def unwrap_rng(state_rng, sharded: bool):
    """Inside shard_map the [1, 2] local block unwraps to this device's
    key; wrap_rng re-wraps for the output state."""
    return state_rng[0] if sharded else state_rng


def wrap_rng(rng, sharded: bool):
    return rng[None] if sharded else rng
