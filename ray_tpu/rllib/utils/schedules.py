"""Parameter schedules (reference: rllib/utils/schedules/ — Constant,
Linear, Piecewise, Exponential)."""
from __future__ import annotations

import math
from typing import List, Sequence, Tuple


class Schedule:
    def value(self, t: int) -> float:
        raise NotImplementedError

    def __call__(self, t: int) -> float:
        return self.value(t)


class ConstantSchedule(Schedule):
    def __init__(self, value: float):
        self._v = value

    def value(self, t: int) -> float:
        return self._v


class LinearSchedule(Schedule):
    def __init__(self, schedule_timesteps: int, initial_p: float = 1.0,
                 final_p: float = 0.0):
        self.T = schedule_timesteps
        self.initial = initial_p
        self.final = final_p

    def value(self, t: int) -> float:
        frac = min(max(t, 0) / self.T, 1.0)
        return self.initial + frac * (self.final - self.initial)


class ExponentialSchedule(Schedule):
    def __init__(self, schedule_timesteps: int, initial_p: float = 1.0,
                 decay_rate: float = 0.1):
        self.T = schedule_timesteps
        self.initial = initial_p
        self.decay = decay_rate

    def value(self, t: int) -> float:
        return self.initial * self.decay ** (t / self.T)


class PiecewiseSchedule(Schedule):
    def __init__(self, endpoints: Sequence[Tuple[int, float]],
                 outside_value: float = None):
        self.endpoints = sorted(endpoints)
        self.outside_value = outside_value

    def value(self, t: int) -> float:
        for (l, lv), (r, rv) in zip(self.endpoints, self.endpoints[1:]):
            if l <= t < r:
                alpha = (t - l) / (r - l)
                return lv + alpha * (rv - lv)
        if t < self.endpoints[0][0] or self.outside_value is None:
            if t >= self.endpoints[-1][0]:
                return self.endpoints[-1][1]
            return self.endpoints[0][1]
        return self.outside_value
