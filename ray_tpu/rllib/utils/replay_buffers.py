"""Replay buffers (reference: rllib/utils/replay_buffers/ +
rllib/execution/segment_tree.py): uniform ReplayBuffer and
PrioritizedReplayBuffer over sum/min segment trees."""
from __future__ import annotations

import random
from typing import Any, List, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class SegmentTree:
    def __init__(self, capacity: int, op, neutral: float):
        assert capacity > 0 and capacity & (capacity - 1) == 0, \
            "capacity must be a power of 2"
        self.capacity = capacity
        self.op = op
        self.tree = np.full(2 * capacity, neutral, np.float64)
        self.neutral = neutral

    def __setitem__(self, idx: int, val: float):
        idx += self.capacity
        self.tree[idx] = val
        idx //= 2
        while idx >= 1:
            self.tree[idx] = self.op(self.tree[2 * idx], self.tree[2 * idx + 1])
            idx //= 2

    def __getitem__(self, idx: int) -> float:
        return float(self.tree[idx + self.capacity])

    def reduce(self) -> float:
        return float(self.tree[1])


class SumSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.add, 0.0)

    def find_prefixsum_idx(self, prefixsum: float) -> int:
        idx = 1
        while idx < self.capacity:
            if self.tree[2 * idx] > prefixsum:
                idx = 2 * idx
            else:
                prefixsum -= self.tree[2 * idx]
                idx = 2 * idx + 1
        return idx - self.capacity


class MinSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.minimum, float("inf"))


class ReplayBuffer:
    def __init__(self, capacity: int = 10000, seed: Optional[int] = None):
        self.capacity = capacity
        self._storage: List[Any] = []
        self._next_idx = 0
        self.rng = random.Random(seed)

    def __len__(self):
        return len(self._storage)

    def add(self, item: Any):
        if self._next_idx >= len(self._storage):
            self._storage.append(item)
        else:
            self._storage[self._next_idx] = item
        self._next_idx = (self._next_idx + 1) % self.capacity

    def sample(self, num_items: int) -> SampleBatch:
        idxes = [self.rng.randrange(len(self._storage))
                 for _ in range(num_items)]
        return SampleBatch.concat_samples([self._storage[i] for i in idxes])


class PrioritizedReplayBuffer(ReplayBuffer):
    def __init__(self, capacity: int = 10000, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        cap2 = 1
        while cap2 < capacity:
            cap2 *= 2
        self._sum = SumSegmentTree(cap2)
        self._min = MinSegmentTree(cap2)
        self._max_priority = 1.0
        self.alpha = alpha

    def add(self, item: Any, priority: Optional[float] = None):
        idx = self._next_idx
        super().add(item)
        p = (priority if priority is not None else self._max_priority)
        self._sum[idx] = p ** self.alpha
        self._min[idx] = p ** self.alpha

    def sample(self, num_items: int, beta: float = 0.4):
        """Returns (batch, idxes, is_weights)."""
        idxes = []
        total = self._sum.reduce()
        for _ in range(num_items):
            mass = self.rng.random() * total
            idxes.append(self._sum.find_prefixsum_idx(mass))
        p_min = self._min.reduce() / total
        max_weight = (p_min * len(self._storage)) ** (-beta)
        weights = np.array([
            ((self._sum[i] / total) * len(self._storage)) ** (-beta)
            / max_weight
            for i in idxes
        ], np.float32)
        batch = SampleBatch.concat_samples([self._storage[i] for i in idxes])
        return batch, idxes, weights

    def update_priorities(self, idxes: List[int], priorities: np.ndarray):
        for i, p in zip(idxes, priorities):
            p = float(max(p, 1e-6))
            self._sum[i] = p ** self.alpha
            self._min[i] = p ** self.alpha
            self._max_priority = max(self._max_priority, p)
