"""Replay buffers (reference: rllib/utils/replay_buffers/ +
rllib/execution/segment_tree.py): uniform ReplayBuffer and
PrioritizedReplayBuffer over sum/min segment trees.

The trees carry BOTH the scalar reference ops (``__setitem__`` /
``find_prefixsum_idx`` — the textbook per-item loops) and vectorized
batch ops (``set_many`` / ``find_prefixsum_idx_many`` — one numpy
level-by-level descent for a whole batch of draws, one bottom-up
propagation wave for a whole batch of priority writes).  The vectorized
ops are float-identical to running the scalar ops in sequence (same
float64 arithmetic in the same order down each root-to-leaf path), which
tests/test_replay_plane.py pins at fixed seed; they are what the
distributed replay plane's shards run per sample/update batch."""
from __future__ import annotations

import random
from typing import Any, List, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class SegmentTree:
    def __init__(self, capacity: int, op, neutral: float):
        assert capacity > 0 and capacity & (capacity - 1) == 0, \
            "capacity must be a power of 2"
        self.capacity = capacity
        self.op = op
        self.tree = np.full(2 * capacity, neutral, np.float64)
        self.neutral = neutral

    def __setitem__(self, idx: int, val: float):
        idx += self.capacity
        self.tree[idx] = val
        idx //= 2
        while idx >= 1:
            self.tree[idx] = self.op(self.tree[2 * idx], self.tree[2 * idx + 1])
            idx //= 2

    def __getitem__(self, idx: int) -> float:
        return float(self.tree[idx + self.capacity])

    def set_many(self, idxs: np.ndarray, vals: np.ndarray) -> None:
        """Batched ``self[i] = v``: write all leaves, then recompute each
        touched internal node exactly once per level (one wave up the
        tree) instead of one root-walk per item.  Duplicate indices keep
        the LAST value, matching the sequential scalar loop."""
        idxs = np.asarray(idxs, np.int64)
        vals = np.asarray(vals, np.float64)
        if idxs.size == 0:
            return
        # Deterministic last-write-wins under duplicates: unique() on the
        # reversed stream keeps each index's final value.
        rev_idx = idxs[::-1]
        uniq, first_pos = np.unique(rev_idx, return_index=True)
        leaves = uniq + self.capacity
        self.tree[leaves] = vals[::-1][first_pos]
        nodes = np.unique(leaves >> 1)
        while nodes.size and nodes[0] >= 1:
            self.tree[nodes] = self.op(self.tree[2 * nodes],
                                       self.tree[2 * nodes + 1])
            nodes = np.unique(nodes >> 1)

    def value_many(self, idxs: np.ndarray) -> np.ndarray:
        """Batched leaf read."""
        return self.tree[np.asarray(idxs, np.int64) + self.capacity]

    def reduce(self) -> float:
        return float(self.tree[1])


class SumSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.add, 0.0)

    def find_prefixsum_idx(self, prefixsum: float) -> int:
        idx = 1
        while idx < self.capacity:
            if self.tree[2 * idx] > prefixsum:
                idx = 2 * idx
            else:
                prefixsum -= self.tree[2 * idx]
                idx = 2 * idx + 1
        return idx - self.capacity

    def find_prefixsum_idx_many(self, prefixsums: np.ndarray) -> np.ndarray:
        """Batched prefix-sum descent: one level of the tree per numpy
        step for the WHOLE batch.  Per-item arithmetic is identical to
        the scalar walk (same compares, same float64 subtractions in the
        same order), so draws match the scalar reference bit-for-bit."""
        ps = np.asarray(prefixsums, np.float64).copy()
        if ps.size == 0:
            return np.zeros(0, np.int64)
        idx = np.ones(ps.shape, np.int64)
        while idx[0] < self.capacity:  # all lanes descend in lockstep
            left = self.tree[2 * idx]
            go_left = left > ps
            ps = np.where(go_left, ps, ps - left)
            idx = np.where(go_left, 2 * idx, 2 * idx + 1)
        return idx - self.capacity


class MinSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.minimum, float("inf"))


class ReplayBuffer:
    def __init__(self, capacity: int = 10000, seed: Optional[int] = None):
        self.capacity = capacity
        self._storage: List[Any] = []
        self._next_idx = 0
        self.rng = random.Random(seed)

    def __len__(self):
        return len(self._storage)

    def add(self, item: Any):
        if self._next_idx >= len(self._storage):
            self._storage.append(item)
        else:
            self._storage[self._next_idx] = item
        self._next_idx = (self._next_idx + 1) % self.capacity

    def sample(self, num_items: int) -> SampleBatch:
        idxes = [self.rng.randrange(len(self._storage))
                 for _ in range(num_items)]
        return SampleBatch.concat_samples([self._storage[i] for i in idxes])


class PrioritizedReplayBuffer(ReplayBuffer):
    def __init__(self, capacity: int = 10000, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        cap2 = 1
        while cap2 < capacity:
            cap2 *= 2
        self._sum = SumSegmentTree(cap2)
        self._min = MinSegmentTree(cap2)
        self._max_priority = 1.0
        self.alpha = alpha

    def add(self, item: Any, priority: Optional[float] = None):
        idx = self._next_idx
        super().add(item)
        p = (priority if priority is not None else self._max_priority)
        self._sum[idx] = p ** self.alpha
        self._min[idx] = p ** self.alpha

    def _draw_masses(self, num_items: int) -> np.ndarray:
        """The draw sequence: one rng.random() per item (kept scalar so
        vectorized and reference sampling consume the seed identically)."""
        total = self._sum.reduce()
        return np.array([self.rng.random() * total
                         for _ in range(num_items)], np.float64)

    def sample(self, num_items: int, beta: float = 0.4):
        """Returns (batch, idxes, is_weights).  One vectorized descent
        for the whole batch of draws + one vectorized weight computation
        (the scalar-loop reference survives as sample_reference)."""
        masses = self._draw_masses(num_items)
        idxes_arr = self._sum.find_prefixsum_idx_many(masses)
        total = self._sum.reduce()
        n = len(self._storage)
        p_min = self._min.reduce() / total
        max_weight = (p_min * n) ** (-beta)
        p_sample = self._sum.value_many(idxes_arr) / total
        weights = ((p_sample * n) ** (-beta) / max_weight).astype(np.float32)
        idxes = [int(i) for i in idxes_arr]
        batch = SampleBatch.concat_samples([self._storage[i] for i in idxes])
        return batch, idxes, weights

    def sample_reference(self, num_items: int, beta: float = 0.4):
        """The pre-vectorization scalar loop, kept as the regression
        oracle: tests assert sample() returns identical draws/weights for
        an identically-seeded buffer."""
        idxes = []
        total = self._sum.reduce()
        for _ in range(num_items):
            mass = self.rng.random() * total
            idxes.append(self._sum.find_prefixsum_idx(mass))
        p_min = self._min.reduce() / total
        max_weight = (p_min * len(self._storage)) ** (-beta)
        weights = np.array([
            ((self._sum[i] / total) * len(self._storage)) ** (-beta)
            / max_weight
            for i in idxes
        ], np.float32)
        batch = SampleBatch.concat_samples([self._storage[i] for i in idxes])
        return batch, idxes, weights

    def update_priorities(self, idxes: List[int], priorities: np.ndarray):
        """Batched priority write: two set_many waves (sum + min trees)
        instead of two root-walks per item."""
        idx_arr = np.asarray(idxes, np.int64)
        p = np.maximum(np.asarray(priorities, np.float64), 1e-6)
        pa = p ** self.alpha
        self._sum.set_many(idx_arr, pa)
        self._min.set_many(idx_arr, pa)
        if p.size:
            self._max_priority = max(self._max_priority, float(p.max()))

    def update_priorities_reference(self, idxes: List[int],
                                    priorities: np.ndarray):
        """Scalar reference for update_priorities (regression oracle)."""
        for i, p in zip(idxes, priorities):
            p = float(max(p, 1e-6))
            self._sum[i] = p ** self.alpha
            self._min[i] = p ** self.alpha
            self._max_priority = max(self._max_priority, p)
