"""Observation filters (reference: rllib/utils/filter.py — MeanStdFilter
with cross-worker sync via apply_changes/sync)."""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class RunningStat:
    """Welford online mean/var, mergeable across workers."""

    def __init__(self, shape: Tuple[int, ...] = ()):
        self.n = 0
        self.mean = np.zeros(shape, np.float64)
        self.m2 = np.zeros(shape, np.float64)

    def push(self, x: np.ndarray):
        x = np.asarray(x, np.float64)
        self.n += 1
        delta = x - self.mean
        self.mean += delta / self.n
        self.m2 += delta * (x - self.mean)

    def merge(self, other: "RunningStat"):
        if other.n == 0:
            return
        n = self.n + other.n
        delta = other.mean - self.mean
        self.mean = self.mean + delta * other.n / n
        self.m2 = self.m2 + other.m2 + delta ** 2 * self.n * other.n / n
        self.n = n

    @property
    def var(self) -> np.ndarray:
        return self.m2 / self.n if self.n > 1 else np.ones_like(self.mean)

    @property
    def std(self) -> np.ndarray:
        return np.sqrt(np.maximum(self.var, 1e-12))

    def copy(self) -> "RunningStat":
        s = RunningStat(self.mean.shape)
        s.n, s.mean, s.m2 = self.n, self.mean.copy(), self.m2.copy()
        return s


class MeanStdFilter:
    def __init__(self, shape: Tuple[int, ...], demean: bool = True,
                 destd: bool = True, clip: Optional[float] = 10.0):
        self.shape = shape
        self.demean = demean
        self.destd = destd
        self.clip = clip
        self.stat = RunningStat(shape)
        self._delta = RunningStat(shape)  # changes since last sync

    def __call__(self, x: np.ndarray, update: bool = True) -> np.ndarray:
        x = np.asarray(x, np.float64)
        if update:
            if x.shape == self.shape:
                self.stat.push(x)
                self._delta.push(x)
            else:  # batched
                for row in x:
                    self.stat.push(row)
                    self._delta.push(row)
        out = x
        if self.demean:
            out = out - self.stat.mean
        if self.destd:
            out = out / self.stat.std
        if self.clip is not None:
            out = np.clip(out, -self.clip, self.clip)
        return out.astype(np.float32)

    # ---- cross-worker sync protocol (reference filter.py) ----
    def collect_delta(self) -> RunningStat:
        d, self._delta = self._delta, RunningStat(self.shape)
        return d

    def apply_delta(self, delta: RunningStat):
        self.stat.merge(delta)

    def sync(self, other: "MeanStdFilter"):
        self.stat = other.stat.copy()
