"""`rllib train` CLI (reference: rllib/train.py + the tuned_examples
yaml format): run any registered algorithm from flags or a yaml/json
experiment file, with stop criteria and checkpointing.

Usage::

    python -m ray_tpu.rllib.train --algo PPO --env CartPole-v1 \
        --stop-reward 150 --stop-iters 120 --checkpoint-dir /tmp/ckpt
    python -m ray_tpu.rllib.train -f cartpole-ppo.yaml

Yaml format (reference: rllib/tuned_examples/*.yaml)::

    cartpole-ppo:
      run: PPO
      env: CartPole-v1
      stop: {episode_reward_mean: 150, training_iteration: 120}
      config:
        lr: 0.0003
        num_envs: 64
        model: {fcnet_hiddens: [64, 64]}
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional


def apply_config(cfg, config: Dict[str, Any]):
    """Map a tuned-example config dict onto an AlgorithmConfig: `model`
    goes through .training(model=...) (validated keys), everything else
    must be an existing attribute — typos fail loudly like the builder."""
    for k, v in config.items():
        if k == "model":
            cfg.training(model=v)
        elif hasattr(cfg, k):
            setattr(cfg, k, v)
        else:
            raise ValueError(f"unknown config key {k!r} for "
                             f"{type(cfg).__name__}")
    return cfg


def run_experiment(run: str, env: str, config: Optional[Dict[str, Any]] = None,
                   stop: Optional[Dict[str, Any]] = None,
                   checkpoint_dir: Optional[str] = None,
                   verbose: bool = True) -> Dict[str, Any]:
    """Train until a stop criterion fires; returns the final metrics
    (plus `checkpoint_path` if a checkpoint dir was given)."""
    from ray_tpu.rllib import get_algorithm_config

    cfg = get_algorithm_config(run).environment(env)
    apply_config(cfg, config or {})
    algo = cfg.build()
    stop = stop or {}
    max_iters = int(stop.get("training_iteration", 100))
    reward_stop = stop.get("episode_reward_mean")
    ts_stop = stop.get("num_env_steps_sampled")
    metrics: Dict[str, Any] = {}
    best = float("-inf")
    for _ in range(max_iters):
        metrics = algo.train()
        r = metrics.get("episode_reward_mean", float("nan"))
        if r == r:
            best = max(best, r)
        if verbose:
            print(f"iter {metrics['training_iteration']}: "
                  f"reward={r if r == r else float('nan'):.2f} "
                  f"steps={metrics.get('num_env_steps_sampled', 0)}",
                  file=sys.stderr)
        if reward_stop is not None and r == r and r >= reward_stop:
            break
        if ts_stop is not None \
                and metrics.get("num_env_steps_sampled", 0) >= ts_stop:
            break
    metrics["best_episode_reward_mean"] = best
    if checkpoint_dir:
        path = algo.save_checkpoint().to_directory(checkpoint_dir)
        metrics["checkpoint_path"] = path
    algo.stop()
    return metrics


def _json_safe(obj):
    """NaN/±inf → None: json.dumps would otherwise emit literals that
    strict JSON consumers (jq, most non-Python parsers) reject."""
    import math

    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def _load_experiments(path: str) -> Dict[str, dict]:
    import yaml

    with open(path) as f:
        if path.endswith(".json"):
            return json.load(f)
        return yaml.safe_load(f)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rllib train", description=__doc__)
    p.add_argument("-f", "--file", help="yaml/json experiment file")
    p.add_argument("--algo", "--run", dest="algo",
                   help="registered algorithm name (PPO, IMPALA, ...)")
    p.add_argument("--env", help="environment name")
    p.add_argument("--config", default="{}",
                   help="JSON dict of AlgorithmConfig overrides")
    p.add_argument("--stop-iters", type=int, default=100)
    p.add_argument("--stop-reward", type=float, default=None)
    p.add_argument("--stop-timesteps", type=int, default=None)
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args(argv)

    if args.file:
        import os

        experiments = _load_experiments(args.file)
        out = {}
        for name, exp in experiments.items():
            print(f"== running {name} ==", file=sys.stderr)
            # Per-experiment subdirectory: a shared dir would overwrite
            # earlier experiments' checkpoints.
            ckpt = (os.path.join(args.checkpoint_dir, name)
                    if args.checkpoint_dir else None)
            out[name] = run_experiment(
                exp["run"], exp["env"], exp.get("config"),
                exp.get("stop"), ckpt)
        print(json.dumps(_json_safe(out), default=str))
        return 0
    if not args.algo or not args.env:
        p.error("either -f FILE or both --algo and --env are required")
    stop: Dict[str, Any] = {"training_iteration": args.stop_iters}
    if args.stop_reward is not None:
        stop["episode_reward_mean"] = args.stop_reward
    if args.stop_timesteps is not None:
        stop["num_env_steps_sampled"] = args.stop_timesteps
    metrics = run_experiment(args.algo, args.env,
                             json.loads(args.config), stop,
                             args.checkpoint_dir)
    print(json.dumps(_json_safe(metrics), default=str))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
