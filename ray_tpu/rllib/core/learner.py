"""JaxLearner / LearnerGroup: gradient updates on the mesh.

Reference: the new-stack RLTrainer/TrainerRunner
(rllib/core/rl_trainer/rl_trainer.py:51, trainer_runner.py:24), whose DDP
wrap + BackendExecutor bootstrap (torch_rl_trainer.py:139) is replaced here
by: params sharded/replicated on a jax mesh, batch sharded on the data axes,
gradients reduced by XLA inside the jitted update.  A LearnerGroup over
multiple hosts is the same code after jax.distributed.initialize — the mesh
just gets bigger (see ray_tpu/train/jax/config.py).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.mesh import MeshSpec, make_mesh
from ray_tpu.parallel.sharding import batch_sharding, replicated


def metrics_to_host(metrics: Dict[str, Any]) -> Dict[str, float]:
    """One batched device->host fetch of a metrics dict (lazy jax scalars
    from JaxLearner.update) into plain floats."""
    host = jax.device_get(metrics)
    return {k: (float(v) if hasattr(v, "__float__") else v)
            for k, v in host.items()}


class JaxLearner:
    """Holds params + optimizer state on a mesh; `update(batch)` runs one
    jitted SGD pass with in-graph gradient reduction."""

    def __init__(self, module, loss_fn: Callable, optimizer=None,
                 mesh=None, example_obs=None, seed: int = 0):
        self.module = module
        self.loss_fn = loss_fn
        self.tx = optimizer or optax.adam(5e-5)
        self.mesh = mesh or make_mesh(MeshSpec({"data": -1}))
        key = jax.random.PRNGKey(seed)
        params = module.init(key, example_obs)
        self.params = jax.device_put(params, replicated(self.mesh))
        self.opt_state = jax.device_put(self.tx.init(self.params),
                                        replicated(self.mesh))
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1))

    def _update_impl(self, params, opt_state, batch):
        def total_loss(p):
            return self.loss_fn(p, self.module, batch)

        (loss, aux), grads = jax.value_and_grad(total_loss, has_aux=True)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, aux

    def update(self, batch: Dict[str, Any]) -> Dict[str, float]:
        data_size = 1
        for a in ("data", "fsdp"):
            if a in self.mesh.axis_names:
                data_size *= self.mesh.shape[a]

        def place(v):
            v = jnp.asarray(v)
            if v.ndim >= 1 and v.shape[0] % max(1, data_size) == 0:
                return jax.device_put(v, batch_sharding(self.mesh, v.ndim))
            return jax.device_put(v, replicated(self.mesh))

        batch = {k: place(v) for k, v in batch.items()}
        self.params, self.opt_state, loss, aux = self._update(
            self.params, self.opt_state, batch)
        # Metrics stay ON DEVICE: every device->host read is a full transfer
        # round-trip (~0.1s on some backends), and callers run this in a
        # minibatch loop where only the last value matters.  Convert with
        # metrics_to_host() (one batched fetch) at iteration end.
        return {"total_loss": loss, **aux}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params):
        self.params = jax.device_put(params, replicated(self.mesh))


class LearnerGroup:
    """Single-host form: one in-process learner driving the whole local
    mesh.  For multi-host, see DistributedLearnerGroup (same API over a
    MeshGroup — the reference TrainerRunner shape,
    rllib/core/rl_trainer/trainer_runner.py:24)."""

    def __init__(self, learner: JaxLearner):
        self.learner = learner

    def update(self, batch):
        return self.learner.update(batch)

    def get_weights(self):
        return self.learner.get_weights()

    def shutdown(self):
        pass


def _build_learner(state, factory):
    state["learner"] = factory()
    return True


def _learner_update(state, batch):
    # Cross-process boundary: results are pickled, so fetch to host here
    # (one batched transfer per update call).
    return metrics_to_host(state["learner"].update(batch))


def _learner_update_device(state, batch):
    # Pipelined form: metrics stay device-resident (lazy jax scalars);
    # the MeshWorker host-converts them only on fetch steps, so the
    # in-between steps never pay a device_get or a payload pickle.
    return state["learner"].update(batch)


def _learner_get_weights(state):
    return state["learner"].get_weights()


def _learner_set_weights(state, weights):
    state["learner"].set_weights(weights)
    return True


def _learner_shard_save(state, root, step, sync):
    """Per-rank sharded checkpoint of the learner params: one bounded
    device→host snapshot, then chunk/hash/write — synchronously, or on
    this rank's background persist thread (``sync=False``), so a save
    riding the step pipeline releases its slot after the snapshot and
    never stalls the donated update stream."""
    import os

    from ray_tpu.checkpoint.saver import ShardWriter

    rank = int(os.environ.get("RTPU_RANK", "0"))
    world = int(os.environ.get("RTPU_WORLD_SIZE", "1"))
    writer = state.get("_ckpt_writer")
    if writer is None or writer.root != root:
        writer = ShardWriter(root, rank, world)
        state["_ckpt_writer"] = writer
    snap = writer.snapshot(state["learner"].params)
    if sync:
        writer.persist(snap, step)
    else:
        writer.persist_async(snap, step)
    return {"rank": rank, "step": int(step)}


def _learner_shard_restore(state, root, step):
    """Restore this rank's learner params from the latest (or given)
    committed manifest.  Params are replicated across the gang, so every
    rank loads the full tree — which is also why an N-rank checkpoint
    restores onto an M-rank gang unchanged (resharded restore)."""
    from ray_tpu.checkpoint.restore import restore_tree

    learner = state["learner"]
    learner.set_weights(
        restore_tree(root, step=step, target=learner.get_weights()))
    return True


class DistributedLearnerGroup:
    """Multi-host LearnerGroup: one learner process per TPU host, gang-
    scheduled as a MeshGroup, all hosts running the same pjit update over
    one global mesh (gradients reduced in-graph by XLA over ICI/DCN).

    The reference bootstraps its TrainerRunner through Train's
    BackendExecutor and wraps each RLTrainer in Torch DDP
    (rllib/core/rl_trainer/torch/torch_rl_trainer.py:139); here the DDP
    wrapper dissolves — after the MeshGroup rendezvous the per-host
    JaxLearner's mesh simply spans every host's devices.

    `learner_factory` must be a picklable zero-arg callable returning a
    JaxLearner; it runs once inside each host process after rendezvous.

    Fault tolerance: with ``max_group_restarts > 0`` a rank death during
    ``update()`` triggers a gang rebuild (see MeshGroup's fault-tolerance
    docs); the ``on_restart`` hook re-materializes the learner in every
    fresh host process and re-broadcasts the last driver-cached weights
    (``checkpoint_weights()`` refreshes the cache), so training resumes
    instead of silently restarting from a re-initialized policy.
    """

    def __init__(self, learner_factory, num_hosts=1,
                 resources_per_host=None, platform=None,
                 local_device_count=None, max_group_restarts: int = 0,
                 pipeline_depth: int = 0, metrics_interval: int = 1,
                 checkpoint_root: Optional[str] = None,
                 checkpoint_keep: Optional[int] = None):
        from ray_tpu.parallel.mesh_group import MeshGroup

        # Elastic range: num_hosts may be (min, max); the gang starts at
        # max and resize() keeps it inside the range.
        if isinstance(num_hosts, (tuple, list)):
            self.min_hosts, self.max_hosts = int(num_hosts[0]), \
                int(num_hosts[1])
            num_hosts = self.max_hosts
        else:
            self.min_hosts = self.max_hosts = int(num_hosts)
        self._factory = learner_factory
        self._last_weights = None
        self._last_metrics: Optional[Dict[str, float]] = None
        self._weight_steps: set = set()
        self._pipeline = None
        # Sharded checkpointing (checkpoint_root set): every rank persists
        # its own shard into the store; the driver only commits manifests.
        self._ckpt_root = checkpoint_root
        self._ckpt_keep = checkpoint_keep
        self._ckpt_step = 0
        self._ckpt_pipe_steps: Dict[int, Tuple[int, bool]] = {}
        self._committer = None
        self.group = MeshGroup(num_hosts, resources_per_host,
                               platform=platform,
                               local_device_count=local_device_count,
                               max_group_restarts=max_group_restarts,
                               pipeline_depth=max(1, pipeline_depth))
        if checkpoint_root is not None:
            from ray_tpu.checkpoint.coordinator import AsyncCommitter

            self._committer = AsyncCommitter()
            # In-flight async saves die with a gang rebuild — cancel their
            # commits so a half-persisted step can never publish.
            self.group.add_restart_hook(
                lambda g: self._committer.cancel_pending())
        self.group.run_stateful(_build_learner, learner_factory)
        if pipeline_depth > 0:
            # Zero-sync hot path: updates stream through a bounded window,
            # the driver never blocks per step, and metrics arrive every
            # metrics_interval-th step (see mesh_group.StepPipeline).
            self._pipeline = self.group.pipeline(
                depth=pipeline_depth, metrics_interval=metrics_interval,
                on_restart=self._on_restart, on_result=self._on_pipe_result)

    def _on_restart(self, group):
        """After a gang rebuild the new host processes hold empty state:
        re-build the learner on every rank, then restore the latest
        COMMITTED sharded checkpoint (when a checkpoint_root is set and
        holds one — per-rank disk reads, no driver broadcast), falling
        back to re-broadcasting the last driver-cached weights."""
        import ray_tpu

        group.run_stateful(_build_learner, self._factory)
        if self._ckpt_root is not None:
            from ray_tpu.checkpoint import manifest as mf

            if mf.latest_committed_step(self._ckpt_root) is not None:
                group.run_stateful(_learner_shard_restore,
                                   self._ckpt_root, None)
                return
        if self._last_weights is not None:
            # One put, num_hosts borrowers: each rank resolves the same
            # store object zero-copy instead of the submit path
            # serializing the weights once per host.
            group.run_stateful(_learner_set_weights,
                               ray_tpu.put(self._last_weights))

    def _commit_sharded(self, step: int) -> None:
        from ray_tpu.checkpoint import manifest as mf
        from ray_tpu.checkpoint.coordinator import commit_when_complete

        pending = (self._committer.pending_steps()
                   if self._committer is not None else [])
        commit_when_complete(self._ckpt_root, step, self.group.num_hosts,
                             in_progress=pending)
        if self._ckpt_keep:
            try:
                mf.evict_steps(self._ckpt_root, self._ckpt_keep)
            except Exception:
                pass

    def checkpoint_weights(self, step: Optional[int] = None):
        """Checkpoint the current policy.

        With a ``checkpoint_root``: a per-rank SHARDED save — every host
        snapshots and persists its own shard, the driver commits the
        manifest — so save cost no longer scales with a full-weights
        gather to the driver.  Returns the committed manifest.

        Without one (legacy): pull rank-0 weights into the driver-side
        restore cache and return them."""
        if self._ckpt_root is not None:
            if step is None:
                self._ckpt_step += 1
                step = self._ckpt_step
            else:
                self._ckpt_step = max(self._ckpt_step, int(step))
            if self._pipeline is not None:
                # Ride the step pipeline: the save serializes with the
                # (donating) in-flight updates instead of racing them.
                idx = self._pipeline.submit(_learner_shard_save,
                                            self._ckpt_root, step, True,
                                            fetch=True)
                self._ckpt_pipe_steps[idx] = (step, True)
                self._pipeline.flush()
            else:
                self.group.run_stateful(_learner_shard_save,
                                        self._ckpt_root, step, True)
                self._commit_sharded(step)
            from ray_tpu.checkpoint import manifest as mf

            return mf.read_manifest(self._ckpt_root, step)
        self._last_weights = self.group.run_rank_stateful(
            0, _learner_get_weights)
        return self._last_weights

    def update(self, batch) -> Dict[str, float]:
        """Every host receives the batch and extracts its addressable
        shards (multi-controller SPMD); metrics are identical across hosts
        post-psum, so rank 0's are returned."""
        import ray_tpu

        # One serialization + one store object shared by all hosts (a ref
        # arg resolves zero-copy per host) instead of num_hosts copies.
        batch_ref = ray_tpu.put(batch)
        results = self.group.run_stateful(_learner_update, batch_ref,
                                          on_restart=self._on_restart)
        return results[0]

    def resize(self, num_hosts: int) -> None:
        """Elastically rebuild the learner gang at ``num_hosts`` hosts
        (clamped to the configured ``(min, max)`` range) at an update
        boundary: capture the live rank-0 weights, rebuild the gang
        (fresh processes + rendezvous), re-materialize the learner on
        every rank and re-broadcast the weights as ONE put."""
        import ray_tpu

        n = max(self.min_hosts, min(self.max_hosts, int(num_hosts)))
        if n == self.group.num_hosts:
            return
        if self._pipeline is not None:
            raise RuntimeError(
                "resize() needs the lockstep path (pipeline_depth=0): an "
                "in-flight step window cannot straddle two world sizes")
        self._last_weights = self.group.run_rank_stateful(
            0, _learner_get_weights)
        self.group.resize(n)
        self.group.run_stateful(_build_learner, self._factory)
        self.group.run_stateful(_learner_set_weights,
                                ray_tpu.put(self._last_weights))

    # ---- pipelined update stream (pipeline_depth > 0) ----
    def _on_pipe_result(self, idx: int, res) -> None:
        if idx in self._ckpt_pipe_steps:
            step, synchronous = self._ckpt_pipe_steps.pop(idx)
            if res is None:
                return  # save step lost to a gang restart replay edge
            if synchronous:
                # sync persist ran inside the pipeline step: every shard
                # file already exists, commit is immediate.
                self._commit_sharded(step)
            else:
                # async persist: rank background threads are still
                # writing; a driver thread commits when the shards land.
                self._committer.commit_async(
                    self._ckpt_root, step, self.group.num_hosts,
                    on_commit=lambda m: self._post_async_commit(step))
            return
        if res is None:
            return  # non-fetch step: metrics stayed on device
        if idx in self._weight_steps:
            self._weight_steps.discard(idx)
            self._last_weights = res[0]
        else:
            self._last_metrics = res[0]

    def _post_async_commit(self, step: int) -> None:
        if self._ckpt_keep:
            try:
                from ray_tpu.checkpoint import manifest as mf

                mf.evict_steps(self._ckpt_root, self._ckpt_keep)
            except Exception:
                pass

    def update_async(self, batch) -> Optional[Dict[str, float]]:
        """Pipelined update: dispatches the step and returns immediately
        (blocking only when the in-flight window is full), so the driver
        never gates device compute.  Returns the LATEST drained metrics —
        which lag the submitted step by up to pipeline_depth steps — or
        None before the first fetch step drains."""
        import ray_tpu
        from ray_tpu.util import tracing

        if self._pipeline is None:
            raise RuntimeError(
                "pipelined updates need pipeline_depth > 0 at construction")
        # Driver API boundary: each update step (batch put + gang
        # dispatch + drain spans) rides one distributed trace, rooted
        # at this span.
        with tracing.span("learner.update_async"):
            batch_ref = ray_tpu.put(batch)
            self._pipeline.submit(_learner_update_device, batch_ref)
        return self._last_metrics

    def checkpoint_weights_async(self, step: Optional[int] = None) -> None:
        """Non-blocking checkpoint: rides the step pipeline, so it
        serializes with the (donating) update steps instead of racing
        them, and the driver never blocks.

        With a ``checkpoint_root``: a per-rank sharded save — the pipeline
        step only pays the bounded host snapshot; chunk writes ride each
        rank's background persist thread and a driver thread commits the
        manifest when every shard lands (two-phase: a crash mid-persist
        leaves the previous committed checkpoint as the latest).

        Without one (legacy): a rank-0 weights fetch that lands in the
        driver-side restore cache when its pipeline slot drains."""
        if self._pipeline is None:
            raise RuntimeError(
                "pipelined snapshots need pipeline_depth > 0")
        if self._ckpt_root is not None:
            if step is None:
                self._ckpt_step += 1
                step = self._ckpt_step
            else:
                self._ckpt_step = max(self._ckpt_step, int(step))
            idx = self._pipeline.submit(_learner_shard_save,
                                        self._ckpt_root, step, False,
                                        fetch=True)
            self._ckpt_pipe_steps[idx] = (step, False)
            return
        idx = self._pipeline.submit(_learner_get_weights, fetch=True)
        self._weight_steps.add(idx)

    def flush_updates(self) -> Optional[Dict[str, float]]:
        """Drain every in-flight pipelined step AND publish any pending
        async checkpoint commits; returns the final metrics (the barrier
        to call at iteration end)."""
        if self._pipeline is not None:
            self._pipeline.flush()
        self.flush_checkpoints()
        return self._last_metrics

    def flush_checkpoints(self) -> None:
        """Barrier for async sharded saves: joins rank persist threads
        and pending manifest commits (re-raising a failed commit)."""
        if self._ckpt_root is None:
            return
        from ray_tpu.checkpoint.coordinator import _rank_wait_persisted

        self.group.run_stateful(_rank_wait_persisted, 120.0)
        self._committer.flush()

    def restore_latest(self, step: Optional[int] = None) -> Optional[int]:
        """Restore every rank's learner from the latest (or given)
        committed manifest under ``checkpoint_root``.  Works across gang
        sizes: an N-rank save restores onto this M-rank group (replicated
        params — each rank reads the full tree from the store).  Returns
        the restored step, or None when the store has no commit."""
        if self._ckpt_root is None:
            raise RuntimeError("restore_latest needs checkpoint_root")
        from ray_tpu.checkpoint import manifest as mf

        if step is None:
            step = mf.latest_committed_step(self._ckpt_root)
            if step is None:
                return None
        if self._pipeline is not None:
            self._pipeline.flush()
        self.group.run_stateful(_learner_shard_restore, self._ckpt_root,
                                step, on_restart=self._on_restart)
        self._ckpt_step = max(self._ckpt_step, int(step))
        return int(step)

    def get_weights(self):
        if self._pipeline is not None:
            # Order the read after every in-flight donated update.
            self._pipeline.flush()
        return self.group.run_rank_stateful(0, _learner_get_weights)

    def set_weights(self, weights):
        import ray_tpu

        if self._pipeline is not None:
            # run_stateful bypasses the pipeline's sequence gate: drain
            # first so the broadcast can't interleave with queued updates.
            self._pipeline.flush()
        self._last_weights = weights
        # Broadcast through the object plane: one serialization + one
        # store object shared by every host (same pattern as update()).
        self.group.run_stateful(_learner_set_weights, ray_tpu.put(weights),
                                on_restart=self._on_restart)

    def shutdown(self):
        if self._pipeline is not None:
            try:
                self._pipeline.close(flush=False)
            except Exception:
                pass
            self._pipeline = None
        if self._committer is not None:
            # Workers are about to die: saves that haven't committed yet
            # become orphans for the next save's GC, never partial reads.
            self._committer.cancel_pending()
        self.group.shutdown()
