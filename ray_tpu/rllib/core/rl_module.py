"""RLModule: the model abstraction of the new stack (reference:
rllib/core/rl_module/rl_module.py; jax skeleton the reference already
sketches: rllib/models/jax/).  A module bundles policy + value heads and
exposes forward_inference / forward_exploration / forward_train."""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.mlp import MLP
from ray_tpu.models.nature_cnn import MinAtarCNN, NatureCNN


@dataclasses.dataclass(frozen=True)
class RLModuleSpec:
    obs_dim: Optional[int] = None
    obs_shape: Optional[Tuple[int, ...]] = None  # set for pixel obs
    num_actions: int = 2
    hiddens: Tuple[int, ...] = (64, 64)
    conv: bool = False

    def build(self) -> "DiscreteActorCritic":
        return DiscreteActorCritic(self)

    def example_obs(self, batch: int = 1) -> np.ndarray:
        """A zero observation batch matching this spec's trunk input —
        uint8 frames for the conv trunk (NatureCNN does the /255), flat
        float32 vectors otherwise.  The one place example-obs shape/dtype
        selection lives (actor-mode learner init uses this)."""
        if self.conv:
            return np.zeros((batch,) + tuple(self.obs_shape), np.uint8)
        return np.zeros((batch, self.obs_dim), np.float32)

    @classmethod
    def for_env(cls, env, hiddens: Tuple[int, ...]) -> "RLModuleSpec":
        """The one place pixel-vs-flat trunk selection lives: envs with
        an obs_shape get the CNN trunk, flat envs the MLP (shared by the
        PPO and V-trace families' anakin setups)."""
        obs_shape = getattr(env, "obs_shape", None)
        if obs_shape is not None:
            return cls(obs_shape=tuple(obs_shape),
                       num_actions=env.num_actions, conv=True)
        return cls(obs_dim=env.obs_dim, num_actions=env.num_actions,
                   hiddens=tuple(hiddens))


class DiscreteActorCritic(nn.Module):
    """Categorical policy + value baseline (separate heads, shared trunk for
    pixels, separate trunks for vectors — matching RLlib PPO defaults)."""

    spec: RLModuleSpec

    @nn.compact
    def __call__(self, obs) -> Tuple[jax.Array, jax.Array]:
        s = self.spec
        if s.conv:
            small = (s.obs_shape is not None
                     and min(s.obs_shape[0], s.obs_shape[1]) < 32)
            trunk_net = (MinAtarCNN(out_dim=128) if small
                         else NatureCNN(out_dim=256))
            trunk = trunk_net(obs)
            logits = nn.Dense(s.num_actions, name="pi")(trunk)
            value = nn.Dense(1, name="vf")(trunk)[..., 0]
        else:
            logits = MLP(s.hiddens, s.num_actions, name="pi_mlp")(obs)
            value = MLP(s.hiddens, 1, name="vf_mlp")(obs)[..., 0]
        return logits, value

    # ---- RLModule API ----
    def forward_inference(self, params, obs):
        logits, _ = self.apply(params, obs)
        return jnp.argmax(logits, axis=-1)

    def forward_exploration(self, params, obs, rng):
        logits, value = self.apply(params, obs)
        action = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)
        action_logp = jnp.take_along_axis(logp, action[..., None], -1)[..., 0]
        return action, action_logp, value

    def forward_train(self, params, obs, actions):
        logits, value = self.apply(params, obs)
        logp_all = jax.nn.log_softmax(logits)
        action_logp = jnp.take_along_axis(
            logp_all, actions[..., None].astype(jnp.int32), -1)[..., 0]
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        return action_logp, value, entropy
