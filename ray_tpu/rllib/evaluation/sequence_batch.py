"""Version-stamped sequence batches for the RLHF rollout plane.

The RLHF loop's unit of experience is a *sequence rollout*: a prompt,
the tokens the serving engine sampled after it, the behavior logprob of
every sampled token (captured by the engine's decode step — no second
forward pass), and the weight version each token was sampled under
(``LLMEngine.swap_weights`` stamps).  This module is the bridge between
the engine's per-request rollout dicts and the learner's fixed-shape
arrays:

- :class:`SequenceRollout` — one rollout record plus its scalar reward.
- :func:`split_fresh` — the ``max_weight_staleness`` consumption gate
  (the PR 5 rollout-plane rule applied to sequences): a rollout is
  consumable iff its OLDEST token is within ``max_staleness`` versions
  of the learner's current weights; staler rollouts are dropped, never
  silently trained on.
- :class:`SequenceBatch` — padded ``[B, L]`` arrays (tokens, response
  mask, behavior logprobs, version stamps, rewards) at a FIXED width so
  the learner's train step compiles once.

Mixed-version rollouts (a hot swap landed mid-request) are fine by
construction: the PPO ratio is per-token and each token's behavior
logprob is exact for the weights that actually sampled it.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class SequenceRollout:
    """One engine rollout (see ``LLMEngine.rollout``) plus its reward."""

    prompt: List[int]
    tokens: List[int]
    logprobs: List[float]
    versions: List[int]
    reward: Optional[float] = None

    @classmethod
    def from_engine(cls, record: Dict) -> "SequenceRollout":
        return cls(prompt=list(record["prompt"]),
                   tokens=list(record["tokens"]),
                   logprobs=list(record["logprobs"]),
                   versions=list(record["versions"]))

    @property
    def min_version(self) -> int:
        return min(self.versions) if self.versions else 0

    @property
    def max_version(self) -> int:
        return max(self.versions) if self.versions else 0

    def __len__(self) -> int:
        return len(self.prompt) + len(self.tokens)


def split_fresh(rollouts: Sequence[SequenceRollout], current_version: int,
                max_staleness: int
                ) -> Tuple[List[SequenceRollout], List[SequenceRollout]]:
    """(fresh, stale) under the staleness gate: a rollout is fresh iff
    every token was sampled within ``max_staleness`` versions of
    ``current_version``."""
    fresh, stale = [], []
    for r in rollouts:
        if current_version - r.min_version <= max_staleness:
            fresh.append(r)
        else:
            stale.append(r)
    return fresh, stale


class SequenceBatch:
    """Fixed-shape learner view of a rollout list.

    ``tokens`` [B, L] int32 (prompt + response, zero-padded),
    ``response_mask`` [B, L] f32 (1.0 exactly on sampled-token
    positions), ``behavior_logp`` [B, L] f32 (0 where masked),
    ``versions`` [B, L] int32 (stamps; 0 where masked), ``rewards``
    [B] f32.  ``L`` is ``pad_to`` — keep it constant across loop
    iterations so the learner's jit compiles once.
    """

    FIELDS = ("tokens", "response_mask", "behavior_logp", "versions")

    def __init__(self, tokens: np.ndarray, response_mask: np.ndarray,
                 behavior_logp: np.ndarray, versions: np.ndarray,
                 rewards: np.ndarray):
        self.tokens = tokens
        self.response_mask = response_mask
        self.behavior_logp = behavior_logp
        self.versions = versions
        self.rewards = rewards

    @classmethod
    def from_rollouts(cls, rollouts: Sequence[SequenceRollout],
                      pad_to: int) -> "SequenceBatch":
        if not rollouts:
            raise ValueError("empty rollout list")
        B = len(rollouts)
        longest = max(len(r) for r in rollouts)
        if longest > pad_to:
            raise ValueError(
                f"rollout of length {longest} exceeds pad_to={pad_to}")
        tokens = np.zeros((B, pad_to), np.int32)
        mask = np.zeros((B, pad_to), np.float32)
        logp = np.zeros((B, pad_to), np.float32)
        vers = np.zeros((B, pad_to), np.int32)
        rewards = np.zeros((B,), np.float32)
        for i, r in enumerate(rollouts):
            p, n = len(r.prompt), len(r.tokens)
            tokens[i, :p] = r.prompt
            tokens[i, p:p + n] = r.tokens
            mask[i, p:p + n] = 1.0
            logp[i, p:p + n] = r.logprobs
            vers[i, p:p + n] = r.versions
            rewards[i] = 0.0 if r.reward is None else float(r.reward)
        return cls(tokens, mask, logp, vers, rewards)

    def as_dict(self) -> Dict[str, np.ndarray]:
        return {"tokens": self.tokens, "response_mask": self.response_mask,
                "behavior_logp": self.behavior_logp,
                "versions": self.versions, "rewards": self.rewards}

    def __len__(self) -> int:
        return self.tokens.shape[0]

    @property
    def num_response_tokens(self) -> int:
        return int(self.response_mask.sum())
