"""RolloutWorker actors + WorkerSet (reference:
rllib/evaluation/rollout_worker.py sample :878, worker_set.py:78 with
fault-tolerant sync_weights/sample)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu


@ray_tpu.remote
class RolloutWorker:
    """CPU actor stepping python envs with jax-on-CPU policy inference.

    Weights arrive via the object store (reference: sync_weights broadcast,
    worker_set.py)."""

    def __init__(self, env_name, module_spec, worker_index: int,
                 num_envs: int, fragment_length: int, gamma: float,
                 lambda_: float, seed: int):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        from ray_tpu.rllib.env.py_envs import VectorEnv, make_py_env

        self.env = VectorEnv(lambda: make_py_env(env_name),
                             num_envs, seed + worker_index * 1000)
        self.module = module_spec.build()
        # Pixel (conv) specs keep raw uint8 frames end-to-end — the CNN
        # trunk does the /255; casting to float32 here would both break
        # that normalization and 4x the sample payload.
        self._conv = bool(getattr(module_spec, "conv", False))
        self.params = None
        self.fragment_length = fragment_length
        self.gamma = gamma
        self.lambda_ = lambda_
        self.rng = jax.random.PRNGKey(seed + worker_index)
        self.obs = self._cast(self.env.reset_all())
        self.ep_returns = np.zeros(num_envs)
        self.completed: List[float] = []
        self._explore = jax.jit(self.module.forward_exploration)
        self._value = jax.jit(
            lambda p, o: self.module.apply(p, o)[1])

    def _cast(self, obs: np.ndarray) -> np.ndarray:
        return obs if self._conv else obs.astype(np.float32)

    def set_weights(self, params):
        self.params = params
        return True

    def ping(self):
        return "ok"

    def sample(self):
        """Returns (SampleBatch with GAE columns, completed episode returns)."""
        import jax
        import numpy as np

        from ray_tpu.rllib.policy.sample_batch import SampleBatch

        T = self.fragment_length
        obs_l, act_l, logp_l, val_l, rew_l, done_l = [], [], [], [], [], []
        for _ in range(T):
            self.rng, k = jax.random.split(self.rng)
            action, logp, value = self._explore(self.params, self.obs, k)
            action = np.asarray(action)
            next_obs, reward, done, _ = self.env.step(action)
            obs_l.append(self.obs)
            act_l.append(action)
            logp_l.append(np.asarray(logp))
            val_l.append(np.asarray(value))
            rew_l.append(reward)
            done_l.append(done)
            self.ep_returns += reward
            for i, d in enumerate(done):
                if d:
                    self.completed.append(float(self.ep_returns[i]))
                    self.ep_returns[i] = 0.0
            self.obs = self._cast(next_obs)

        last_value = np.asarray(self._value(self.params, self.obs))
        rewards = np.stack(rew_l)          # [T, N]
        values = np.stack(val_l)
        dones = np.stack(done_l)
        # GAE, time-major vectorized over envs.
        from ray_tpu.rllib.evaluation.postprocessing import gae_jax

        adv, vtarg = gae_jax(rewards, values, dones.astype(np.float32),
                             last_value, self.gamma, self.lambda_)
        n = rewards.size
        obs_arr = np.stack(obs_l)  # [T, N, ...] — pixel shapes preserved
        batch = SampleBatch({
            "obs": obs_arr.reshape((n,) + obs_arr.shape[2:]),
            "actions": np.stack(act_l).reshape(n),
            "action_logp": np.stack(logp_l).reshape(n),
            "vf_preds": values.reshape(n),
            "rewards": rewards.reshape(n),
            "dones": dones.reshape(n),
            "advantages": np.asarray(adv).reshape(n),
            "value_targets": np.asarray(vtarg).reshape(n),
        })
        completed, self.completed = self.completed, []
        return batch, completed

    def sample_timemajor(self):
        """IMPALA fragment: time-major [T, N] tensors + behaviour logp +
        bootstrap value (what V-trace consumes)."""
        import jax
        import numpy as np

        T = self.fragment_length
        obs_l, act_l, logp_l, rew_l, done_l = [], [], [], [], []
        for _ in range(T):
            self.rng, k = jax.random.split(self.rng)
            action, logp, _ = self._explore(self.params, self.obs, k)
            action = np.asarray(action)
            next_obs, reward, done, _ = self.env.step(action)
            obs_l.append(self.obs)
            act_l.append(action)
            logp_l.append(np.asarray(logp))
            rew_l.append(reward)
            done_l.append(done)
            self.ep_returns += reward
            for i, d in enumerate(done):
                if d:
                    self.completed.append(float(self.ep_returns[i]))
                    self.ep_returns[i] = 0.0
            self.obs = self._cast(next_obs)
        last_value = np.asarray(self._value(self.params, self.obs))
        batch = {
            "obs": np.stack(obs_l),                      # [T, N, obs]
            "actions": np.stack(act_l),                  # [T, N]
            "behaviour_logp": np.stack(logp_l),
            "rewards": np.stack(rew_l).astype(np.float32),
            "dones": np.stack(done_l).astype(np.float32),
            "last_value": last_value,
        }
        completed, self.completed = self.completed, []
        return batch, completed


@ray_tpu.remote
class OffPolicyRolloutWorker:
    """CPU actor collecting RAW TRANSITIONS for the replay-family
    algorithms (DQN/SAC/TD3) — the Ape-X shape: rollout actors feed a
    learner-owned replay buffer (reference: ApexDQN's distributed replay
    actors + the learner-thread consumer,
    rllib/execution/multi_gpu_learner_thread.py:20).

    The per-algorithm piece is an `act_factory` (cloudpickled closure)
    returning ``act(params, obs, key, explore_arg) -> action`` — epsilon
    for DQN, noise scale for TD3, unused for SAC's stochastic policy."""

    def __init__(self, env_name, act_factory_blob, worker_index: int,
                 num_envs: int, fragment_length: int, seed: int):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import cloudpickle
        import jax

        from ray_tpu.rllib.env.py_envs import VectorEnv, make_py_env

        self.env = VectorEnv(lambda: make_py_env(env_name),
                             num_envs, seed + worker_index * 1000)
        self.params = None
        self.fragment_length = fragment_length
        self.rng = jax.random.PRNGKey(seed + worker_index)
        # The replay-family networks are flat MLPs: pixel obs flatten to
        # float32 vectors (the pre-pixel-path behavior; a conv replay
        # stack would need obs-shaped buffers end to end).
        self.obs = self._flat(self.env.reset_all())
        self.ep_returns = np.zeros(num_envs)
        self.completed: List[float] = []
        self._act = jax.jit(cloudpickle.loads(act_factory_blob)())

    def _flat(self, obs: np.ndarray) -> np.ndarray:
        return obs.astype(np.float32).reshape(obs.shape[0], -1)

    def set_weights(self, params):
        self.params = params
        return True

    def ping(self):
        return "ok"

    def sample(self, explore_arg: float = 0.0):
        """T steps of raw transitions: column dict + completed returns."""
        import jax

        T = self.fragment_length
        obs_l, act_l, rew_l, nxt_l, done_l = [], [], [], [], []
        for _ in range(T):
            self.rng, k = jax.random.split(self.rng)
            action = np.asarray(self._act(self.params, self.obs, k,
                                          explore_arg))
            next_obs, reward, done, _ = self.env.step(action)
            obs_l.append(self.obs)
            act_l.append(action)
            rew_l.append(reward)
            nxt_l.append(self._flat(next_obs))
            done_l.append(done)
            self.ep_returns += reward
            for i, d in enumerate(done):
                if d:
                    self.completed.append(float(self.ep_returns[i]))
                    self.ep_returns[i] = 0.0
            self.obs = self._flat(next_obs)
        n = np.stack(rew_l).size
        batch = {
            "obs": np.stack(obs_l).reshape(n, -1),
            "actions": np.concatenate(act_l, axis=0)
            if np.asarray(act_l[0]).ndim > 1
            else np.stack(act_l).reshape(n),
            "rewards": np.stack(rew_l).reshape(n).astype(np.float32),
            "next_obs": np.stack(nxt_l).reshape(n, -1),
            "dones": np.stack(done_l).reshape(n).astype(np.float32),
        }
        completed, self.completed = self.completed, []
        return batch, completed


class WorkerSet:
    """Rollout workers behind a fault-tolerant actor manager (reference:
    FaultTolerantActorManager, rllib/utils/actor_manager.py:157 — health
    tracking, probing, and replacement of workers whose restart budget is
    exhausted; num_healthy_workers surfaces in training metrics)."""

    MAX_FAILURES_BEFORE_RECREATE = 2

    def __init__(self, config, module_spec, worker_factory=None):
        self._config = config
        self._module_spec = module_spec
        self._worker_factory = worker_factory
        n = max(1, config.num_rollout_workers)
        self.workers = [self._make_worker(i) for i in range(n)]
        self._failures = [0] * n
        self._weights_ref = None

    def _make_worker(self, i: int):
        if self._worker_factory is not None:
            return self._worker_factory(i)
        c = self._config
        return RolloutWorker.options(max_restarts=1).remote(
            c.env, self._module_spec, i, c.num_envs_per_worker,
            c.rollout_fragment_length, c.gamma, c.lambda_, c.seed)

    def _foreach(self, make_future) -> List[Tuple[int, Any]]:
        """The ONE fault-handling loop: run `make_future(worker)` on every
        worker, harvest results, reset the failure counter on success,
        count failures (replacing exhausted workers), and restore weights
        on replacements AFTER the harvest so one cold-starting actor never
        stalls the others' results.  Returns (index, result) pairs for the
        successes."""
        futures = [(i, make_future(w)) for i, w in enumerate(self.workers)]
        out: List[Tuple[int, Any]] = []
        replaced: List[int] = []
        # Fast path: one batched gather (a single resolve round trip for
        # every store-resident result) — the per-future harvest below only
        # runs when a worker actually failed, to attribute the failure.
        if len(futures) > 1:
            try:
                values = ray_tpu.get_many([f for _, f in futures])
                for (i, _f), v in zip(futures, values):
                    out.append((i, v))
                    self._failures[i] = 0
                return out
            except ray_tpu.exceptions.RayTpuError:
                out = []
        for i, f in futures:
            try:
                out.append((i, ray_tpu.get(f)))
                self._failures[i] = 0
            except ray_tpu.exceptions.RayTpuError:
                if self._count_failure(i):
                    replaced.append(i)
        self._restore_weights(replaced)
        return out

    def _count_failure(self, i: int) -> bool:
        """Count a strike; past the budget, replace the actor entirely
        (the reference recreates workers the restart policy gave up on).
        Returns True when the worker was replaced."""
        self._failures[i] += 1
        if self._failures[i] < self.MAX_FAILURES_BEFORE_RECREATE:
            return False  # the actor restart policy gets another chance
        try:
            ray_tpu.kill(self.workers[i])
        except Exception:
            pass
        self.workers[i] = self._make_worker(i)
        # One strike from another replacement until a success resets it —
        # a worker that can't restore its weights must not look healthy.
        self._failures[i] = self.MAX_FAILURES_BEFORE_RECREATE - 1
        return True

    def _restore_weights(self, indices: List[int]):
        if not indices or self._weights_ref is None:
            return
        futures = [(i, self.workers[i].set_weights.remote(self._weights_ref))
                   for i in indices]
        for i, f in futures:
            try:
                ray_tpu.get(f)
                self._failures[i] = 0
            except ray_tpu.exceptions.RayTpuError:
                self._count_failure(i)

    def report_failure(self, worker):
        """External samplers (IMPALA's async loop) report a dead handle
        they harvested themselves."""
        for i, w in enumerate(self.workers):
            if w is worker:
                if self._count_failure(i):
                    self._restore_weights([i])
                return

    def sync_weights(self, params):
        # One put, N borrowers — the object-store broadcast pattern the
        # reference uses for sync_weights.
        self._weights_ref = ray_tpu.put(params)
        self._foreach(lambda w: w.set_weights.remote(self._weights_ref))

    def probe_health(self) -> int:
        """Ping every worker; failures feed the replacement policy.
        Returns the number of currently-healthy workers."""
        return len(self._foreach(lambda w: w.ping.remote()))

    @property
    def num_healthy_workers(self) -> int:
        return sum(1 for n in self._failures if n == 0)

    def sample_sync(self, *args) -> Tuple[List[Any], List[float]]:
        """synchronous_parallel_sample (reference:
        rllib/execution/rollout_ops.py:21) with dead-worker tolerance.
        Extra args forward to the workers' sample() (the off-policy
        workers take the exploration argument per call)."""
        batches, returns = [], []
        for _i, (b, eps) in self._foreach(
                lambda w: w.sample.remote(*args)):
            batches.append(b)
            returns.extend(eps)
        return batches, returns

    def sample_async(self):
        return [(w, w.sample.remote()) for w in self.workers]

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
