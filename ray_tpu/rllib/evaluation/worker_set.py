"""RolloutWorker actors + WorkerSet (reference:
rllib/evaluation/rollout_worker.py sample :878, worker_set.py:78 with
fault-tolerant sync_weights/sample).

The rollout hot loop writes into preallocated time-major ``[T, N, ...]``
arrays (:class:`FragmentBuffers`) instead of list-append + ``np.stack``,
and the PRNG keys for a fragment are minted in ONE ``jax.random.split``
instead of one dispatch per step.  Weights are versioned: each
``set_weights(params, version)`` commits the params to the worker's
device once (no per-call host->device transfer) and stamps every
subsequent fragment with the version it acted under — the streaming
sampler (sample_stream.py) uses the stamp to bound staleness."""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu


class FragmentBuffers:
    """Preallocated time-major fragment storage, reused across fragments.

    Column arrays are allocated lazily from the first row's shape/dtype as
    ``[T, N, ...]`` and overwritten in place each fragment — the actor
    serializes its reply before the next sample call runs, so reuse never
    races the wire copy.  Halves the hot-loop copies vs append+stack (one
    write per row instead of append now + stack later)."""

    def __init__(self, T: int):
        self.T = T
        self._arrs: Dict[str, np.ndarray] = {}

    def store(self, name: str, t: int, value) -> None:
        arr = self._arrs.get(name)
        if arr is None:
            row = np.asarray(value)
            arr = np.zeros((self.T,) + row.shape, row.dtype)
            self._arrs[name] = arr
        arr[t] = value

    def arrays(self) -> Dict[str, np.ndarray]:
        return dict(self._arrs)


_FRAGMENT_COLS = ("obs", "actions", "action_logp", "vf_preds", "rewards",
                  "dones")


def collect_fragment(env, act_fn, obs, keys, ep_returns, completed,
                     bufs: Optional[FragmentBuffers] = None,
                     cast=lambda o: o):
    """Roll ``len(keys)`` steps of ``env`` under ``act_fn(obs, key) ->
    (action, logp, value)`` (numpy outputs).

    With ``bufs`` rows land in preallocated ``[T, N, ...]`` arrays; with
    ``bufs=None`` the legacy append+``np.stack`` path runs — kept so the
    byte-identity of the two paths stays testable
    (tests/test_rollout_plane.py).  Episode accounting (``ep_returns``
    mutated in place, finished returns appended to ``completed``) is
    shared.  Returns ``(next_obs, cols)`` with cols time-major."""
    if bufs is None:
        lists: Dict[str, list] = {k: [] for k in _FRAGMENT_COLS}
        for t in range(len(keys)):
            action, logp, value = act_fn(obs, keys[t])
            next_obs, reward, done, _ = env.step(action)
            lists["obs"].append(obs)
            lists["actions"].append(action)
            lists["action_logp"].append(logp)
            lists["vf_preds"].append(value)
            lists["rewards"].append(reward)
            lists["dones"].append(done)
            ep_returns += reward
            for i, d in enumerate(done):
                if d:
                    completed.append(float(ep_returns[i]))
                    ep_returns[i] = 0.0
            obs = cast(next_obs)
        return obs, {k: np.stack(v) for k, v in lists.items()}
    for t in range(len(keys)):
        action, logp, value = act_fn(obs, keys[t])
        next_obs, reward, done, _ = env.step(action)
        bufs.store("obs", t, obs)
        bufs.store("actions", t, action)
        bufs.store("action_logp", t, logp)
        bufs.store("vf_preds", t, value)
        bufs.store("rewards", t, reward)
        bufs.store("dones", t, done)
        ep_returns += reward
        for i, d in enumerate(done):
            if d:
                completed.append(float(ep_returns[i]))
                ep_returns[i] = 0.0
        obs = cast(next_obs)
    return obs, bufs.arrays()


@ray_tpu.remote
class RolloutWorker:
    """CPU actor stepping python envs with jax-on-CPU policy inference.

    Weights arrive via the object store (reference: sync_weights broadcast,
    worker_set.py) — one put per weights VERSION, workers apply it between
    fragments (the actor mailbox is FIFO, so a set_weights queued behind K
    in-flight sample calls lands exactly at the next fragment boundary)."""

    def __init__(self, env_name, module_spec, worker_index: int,
                 num_envs: int, fragment_length: int, gamma: float,
                 lambda_: float, seed: int, env_parallelism: str = "serial",
                 env_workers: Optional[int] = None):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        from ray_tpu.rllib.env.py_envs import VectorEnv, make_py_env

        self.env = VectorEnv(lambda: make_py_env(env_name),
                             num_envs, seed + worker_index * 1000,
                             mode=env_parallelism, num_workers=env_workers)
        self.module = module_spec.build()
        # Pixel (conv) specs keep raw uint8 frames end-to-end — the CNN
        # trunk does the /255; casting to float32 here would both break
        # that normalization and 4x the sample payload.
        self._conv = bool(getattr(module_spec, "conv", False))
        self.params = None
        self.fragment_length = fragment_length
        self.gamma = gamma
        self.lambda_ = lambda_
        self.rng = jax.random.PRNGKey(seed + worker_index)
        self.obs = self._cast(self.env.reset_all())
        self.ep_returns = np.zeros(num_envs)
        self.completed: List[float] = []
        self._explore = jax.jit(self.module.forward_exploration)
        self._value = jax.jit(
            lambda p, o: self.module.apply(p, o)[1])
        self._bufs = FragmentBuffers(fragment_length)
        self._weights_version = 0
        self._last_sample_end = 0.0

    def _cast(self, obs: np.ndarray) -> np.ndarray:
        return obs if self._conv else obs.astype(np.float32)

    def set_weights(self, params, version: int = 0):
        import jax

        # Commit once per version: zero-copy store views become device
        # arrays here, so the per-step jit dispatch never re-transfers the
        # params (and the shm-backed numpy views are released promptly).
        self.params = jax.device_put(params)
        self._weights_version = int(version)
        return version

    def ping(self):
        return "ok"

    def pid(self):
        import os

        return os.getpid()

    def sample(self):
        """Returns (SampleBatch with GAE columns, completed episode
        returns) — the lockstep sample_sync shape."""
        batch, completed, _ = self.sample_fragment("gae")
        return batch, completed

    def sample_timemajor(self):
        """IMPALA fragment: time-major [T, N] tensors + behaviour logp +
        bootstrap value (what V-trace consumes)."""
        batch, completed, _ = self.sample_fragment("timemajor")
        return batch, completed

    def sample_fragment(self, kind: str = "gae"):
        """One fragment + production info for the streaming sampler:
        ``(batch, completed_episode_returns, info)`` where info carries
        the weights version the fragment was produced under, wall-clock
        production interval, and the worker's idle gap since its previous
        fragment (the rollout_worker_idle_frac input)."""
        import jax

        t0 = time.time()
        idle = t0 - self._last_sample_end if self._last_sample_end else 0.0
        T = self.fragment_length
        # ONE split per fragment (T keys) instead of one dispatch per step.
        keys = np.asarray(jax.random.split(self.rng, T + 1))
        self.rng = keys[0]
        step_keys = keys[1:]

        def act(obs, key):
            a, logp, v = self._explore(self.params, obs, key)
            return np.asarray(a), np.asarray(logp), np.asarray(v)

        self.obs, cols = collect_fragment(
            self.env, act, self.obs, step_keys, self.ep_returns,
            self.completed, bufs=self._bufs, cast=self._cast)
        last_value = np.asarray(self._value(self.params, self.obs))
        if kind == "timemajor":
            batch = {
                "obs": cols["obs"],                       # [T, N, obs]
                "actions": cols["actions"],               # [T, N]
                "behaviour_logp": cols["action_logp"],
                "rewards": cols["rewards"].astype(np.float32),
                "dones": cols["dones"].astype(np.float32),
                "last_value": last_value,
            }
        elif kind == "gae":
            from ray_tpu.rllib.evaluation.postprocessing import gae_jax
            from ray_tpu.rllib.policy.sample_batch import SampleBatch

            rewards, values = cols["rewards"], cols["vf_preds"]
            dones = cols["dones"]
            adv, vtarg = gae_jax(rewards, values, dones.astype(np.float32),
                                 last_value, self.gamma, self.lambda_)
            n = rewards.size
            obs_arr = cols["obs"]  # [T, N, ...] — pixel shapes preserved
            batch = SampleBatch({
                "obs": obs_arr.reshape((n,) + obs_arr.shape[2:]),
                "actions": cols["actions"].reshape(n),
                "action_logp": cols["action_logp"].reshape(n),
                "vf_preds": values.reshape(n),
                "rewards": rewards.reshape(n),
                "dones": dones.reshape(n),
                "advantages": np.asarray(adv).reshape(n),
                "value_targets": np.asarray(vtarg).reshape(n),
            })
        else:
            raise ValueError(f"unknown fragment kind {kind!r}")
        completed, self.completed = self.completed, []
        t1 = time.time()
        self._last_sample_end = t1
        info = {
            "weights_version": self._weights_version,
            "produce_start": t0,
            "produce_end": t1,
            "idle_s": idle,
            "busy_s": t1 - t0,
            "env_steps": T * self.env.num_envs,
        }
        return batch, completed, info


@ray_tpu.remote
class OffPolicyRolloutWorker:
    """CPU actor collecting RAW TRANSITIONS for the replay-family
    algorithms (DQN/SAC/TD3) — the Ape-X shape: rollout actors feed a
    learner-owned replay buffer (reference: ApexDQN's distributed replay
    actors + the learner-thread consumer,
    rllib/execution/multi_gpu_learner_thread.py:20).

    The per-algorithm piece is an `act_factory` (cloudpickled closure)
    returning ``act(params, obs, key, explore_arg) -> action`` — epsilon
    for DQN, noise scale for TD3, unused for SAC's stochastic policy."""

    def __init__(self, env_name, act_factory_blob, worker_index: int,
                 num_envs: int, fragment_length: int, seed: int,
                 env_parallelism: str = "serial",
                 env_workers: Optional[int] = None):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import cloudpickle
        import jax

        from ray_tpu.rllib.env.py_envs import VectorEnv, make_py_env

        self.env = VectorEnv(lambda: make_py_env(env_name),
                             num_envs, seed + worker_index * 1000,
                             mode=env_parallelism, num_workers=env_workers)
        self.params = None
        self.fragment_length = fragment_length
        self.rng = jax.random.PRNGKey(seed + worker_index)
        # The replay-family networks are flat MLPs: pixel obs flatten to
        # float32 vectors (the pre-pixel-path behavior; a conv replay
        # stack would need obs-shaped buffers end to end).
        self.obs = self._flat(self.env.reset_all())
        self.ep_returns = np.zeros(num_envs)
        self.completed: List[float] = []
        self._act = jax.jit(cloudpickle.loads(act_factory_blob)())
        self._bufs = FragmentBuffers(fragment_length)
        self._weights_version = 0

    def _flat(self, obs: np.ndarray) -> np.ndarray:
        return obs.astype(np.float32).reshape(obs.shape[0], -1)

    def set_weights(self, params, version: int = 0):
        import jax

        self.params = jax.device_put(params)
        self._weights_version = int(version)
        return version

    def ping(self):
        return "ok"

    def sample(self, explore_arg: float = 0.0):
        """T steps of raw transitions: column dict + completed returns."""
        import jax

        T = self.fragment_length
        keys = np.asarray(jax.random.split(self.rng, T + 1))
        self.rng = keys[0]
        bufs = self._bufs
        obs = self.obs
        for t in range(T):
            action = np.asarray(self._act(self.params, obs, keys[t + 1],
                                          explore_arg))
            next_obs, reward, done, _ = self.env.step(action)
            next_flat = self._flat(next_obs)
            bufs.store("obs", t, obs)
            bufs.store("actions", t, action)
            bufs.store("rewards", t, reward)
            bufs.store("next_obs", t, next_flat)
            bufs.store("dones", t, done)
            self.ep_returns += reward
            for i, d in enumerate(done):
                if d:
                    self.completed.append(float(self.ep_returns[i]))
                    self.ep_returns[i] = 0.0
            obs = next_flat
        self.obs = obs
        cols = bufs.arrays()
        n = cols["rewards"].size
        act_arr = cols["actions"]
        batch = {
            "obs": cols["obs"].reshape(n, -1),
            "actions": act_arr.reshape(n, -1)
            if act_arr.ndim > 2 else act_arr.reshape(n),
            "rewards": cols["rewards"].reshape(n).astype(np.float32),
            "next_obs": cols["next_obs"].reshape(n, -1),
            "dones": cols["dones"].reshape(n).astype(np.float32),
        }
        completed, self.completed = self.completed, []
        return batch, completed

    def sample_publish(self, explore_arg: float = 0.0, gamma: float = 0.99,
                       n_step: int = 1):
        """The replay-plane publish path: collect one fragment, fold
        n-step returns HERE (the worker owns the contiguity), publish the
        columns to the object plane with one put_many burst, and return
        only the refs + metadata — transition bytes never ride the RPC
        reply, so the learner's insert path is pure ref bookkeeping."""
        batch, completed = self.sample(explore_arg)
        if n_step > 1:
            from ray_tpu.rllib.execution.replay_plane import compute_nstep

            batch = compute_nstep(batch, len(self.ep_returns), gamma,
                                  n_step)
        cols = sorted(batch)
        refs = ray_tpu.put_many([np.ascontiguousarray(batch[c])
                                 for c in cols])
        meta = {"n": len(batch["rewards"]),
                "version": self._weights_version}
        return dict(zip(cols, refs)), meta, completed


class WorkerSet:
    """Rollout workers behind a fault-tolerant actor manager (reference:
    FaultTolerantActorManager, rllib/utils/actor_manager.py:157 — health
    tracking, probing, and replacement of workers whose restart budget is
    exhausted; num_healthy_workers surfaces in training metrics)."""

    MAX_FAILURES_BEFORE_RECREATE = 2

    def __init__(self, config, module_spec, worker_factory=None):
        self._config = config
        self._module_spec = module_spec
        self._worker_factory = worker_factory
        n = max(1, config.num_rollout_workers)
        self.workers = [self._make_worker(i) for i in range(n)]
        self._failures = [0] * n
        self._weights_ref = None
        self._weights_version = 0
        self.num_replaced = 0

    def _make_worker(self, i: int):
        if self._worker_factory is not None:
            return self._worker_factory(i)
        c = self._config
        return RolloutWorker.options(max_restarts=1).remote(
            c.env, self._module_spec, i, c.num_envs_per_worker,
            c.rollout_fragment_length, c.gamma, c.lambda_, c.seed,
            env_parallelism=getattr(c, "env_parallelism", "serial"),
            env_workers=getattr(c, "num_env_workers", None))

    def _foreach(self, make_future) -> List[Tuple[int, Any]]:
        """The ONE fault-handling loop: run `make_future(worker)` on every
        worker, harvest results, reset the failure counter on success,
        count failures (replacing exhausted workers), and restore weights
        on replacements AFTER the harvest so one cold-starting actor never
        stalls the others' results.  Returns (index, result) pairs for the
        successes."""
        futures = [(i, make_future(w)) for i, w in enumerate(self.workers)]
        out: List[Tuple[int, Any]] = []
        replaced: List[int] = []
        # Fast path: one batched gather (a single resolve round trip for
        # every store-resident result) — the per-future harvest below only
        # runs when a worker actually failed, to attribute the failure.
        if len(futures) > 1:
            try:
                values = ray_tpu.get_many([f for _, f in futures])
                for (i, _f), v in zip(futures, values):
                    out.append((i, v))
                    self._failures[i] = 0
                return out
            except ray_tpu.exceptions.RayTpuError:
                out = []
        for i, f in futures:
            try:
                out.append((i, ray_tpu.get(f)))
                self._failures[i] = 0
            except ray_tpu.exceptions.RayTpuError:
                if self._count_failure(i):
                    replaced.append(i)
        self._restore_weights(replaced)
        return out

    def _count_failure(self, i: int) -> bool:
        """Count a strike; past the budget, replace the actor entirely
        (the reference recreates workers the restart policy gave up on).
        Returns True when the worker was replaced."""
        self._failures[i] += 1
        if self._failures[i] < self.MAX_FAILURES_BEFORE_RECREATE:
            return False  # the actor restart policy gets another chance
        try:
            ray_tpu.kill(self.workers[i])
        except Exception:
            pass
        self.workers[i] = self._make_worker(i)
        self.num_replaced += 1
        # One strike from another replacement until a success resets it —
        # a worker that can't restore its weights must not look healthy.
        self._failures[i] = self.MAX_FAILURES_BEFORE_RECREATE - 1
        return True

    def _restore_weights(self, indices: List[int]):
        if not indices or self._weights_ref is None:
            return
        futures = [(i, self.workers[i].set_weights.remote(
            self._weights_ref, self._weights_version)) for i in indices]
        for i, f in futures:
            try:
                # Bounded: a replacement stuck starting (e.g. rescheduled
                # off a dead node) must strike out, not hang the sampler
                # forever (GetTimeoutError is a RayTpuError).
                ray_tpu.get(f, timeout=60.0)
                self._failures[i] = 0
            except ray_tpu.exceptions.RayTpuError:
                self._count_failure(i)

    def report_failure(self, worker):
        """External samplers report a dead handle they harvested
        themselves."""
        for i, w in enumerate(self.workers):
            if w is worker:
                self.report_failure_index(i)
                return

    def report_failure_index(self, i: int) -> bool:
        """Index-addressed failure report (the streaming sampler's path —
        robust to the handle at slot i having been replaced already).
        Returns True when the report replaced the worker."""
        if self._count_failure(i):
            self._restore_weights([i])
            return True
        return False

    def sync_weights(self, params):
        # One put, N borrowers — the object-store broadcast pattern the
        # reference uses for sync_weights.  Blocking form (lockstep
        # callers); the streaming plane uses broadcast_weights_async.
        self._weights_version += 1
        self._weights_ref = ray_tpu.put(params)
        v = self._weights_version
        self._foreach(lambda w: w.set_weights.remote(self._weights_ref, v))
        return v

    def broadcast_weights_async(self, params) -> int:
        """Versioned non-blocking broadcast: ONE object-store put for the
        version, then a fire-and-forget ``set_weights`` per worker.  The
        actor mailbox is FIFO, so each worker applies the new version at
        its next fragment boundary ("pull between fragments") — the
        driver never waits.  Failures surface through the sample path
        (and replacements are re-seeded from ``_weights_ref``).

        The N concurrent resolutions of the one ref ride the transfer
        plane's cooperative broadcast (transfer_coop_broadcast): each
        receiver advertises its landed chunk ranges and serves them to
        the others, so the owner uploads ~one copy instead of N and
        aggregate bandwidth scales with the worker count."""
        self._weights_version += 1
        self._weights_ref = ray_tpu.put(params)
        for w in self.workers:
            w.set_weights.remote(self._weights_ref, self._weights_version)
        return self._weights_version

    @property
    def weights_version(self) -> int:
        return self._weights_version

    def probe_health(self) -> int:
        """Ping every worker; failures feed the replacement policy.
        Returns the number of currently-healthy workers."""
        return len(self._foreach(lambda w: w.ping.remote()))

    @property
    def num_healthy_workers(self) -> int:
        return sum(1 for n in self._failures if n == 0)

    def publish_sync(self, *args) -> List[Tuple[Any, Dict[str, Any], list]]:
        """sample_sync's replay-plane sibling: every worker publishes its
        fragment to the object plane and replies (refs, meta, completed)
        — same dead-worker tolerance, no payload bytes in the replies."""
        return [r for _i, r in self._foreach(
            lambda w: w.sample_publish.remote(*args))]

    def sample_sync(self, *args) -> Tuple[List[Any], List[float]]:
        """synchronous_parallel_sample (reference:
        rllib/execution/rollout_ops.py:21) with dead-worker tolerance.
        Extra args forward to the workers' sample() (the off-policy
        workers take the exploration argument per call)."""
        batches, returns = [], []
        for _i, (b, eps) in self._foreach(
                lambda w: w.sample.remote(*args)):
            batches.append(b)
            returns.extend(eps)
        return batches, returns

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
