"""SampleStream: the asynchronous rollout plane.

The lockstep actor path (``WorkerSet.sample_sync``) is a barrier loop:
every worker samples, the learner trains while all rollout actors sit
idle, then a blocking weight sync gates the next round.  The Podracer /
Sebulba architecture (arXiv:2104.06272) decouples the two sides so
neither ever waits on the other; this module is that plane for the CPU
rollout actors:

- **Streaming production** — every worker holds up to
  ``max_in_flight_per_worker`` queued ``sample_fragment`` calls (a
  per-worker :class:`~ray_tpu.parallel.flow.Window`, the shared
  bounded-window backpressure primitive under the mesh StepPipeline and
  the whole dataflow substrate).
  The actor mailbox is FIFO, so a worker finishes one fragment and rolls
  straight into the next with no driver round trip in between; the
  learner consumes fragments as they land via :meth:`next_fragment`.
- **Versioned weight broadcast** — :meth:`publish_weights` performs ONE
  object-store put per version (riding the batched object plane;
  N workers borrow one ref) and fire-and-forget ``set_weights`` sends.
  Workers apply the newest version at their next fragment boundary and
  stamp every fragment with the version it acted under.
- **Bounded staleness** — fragments produced under weights older than
  ``max_weight_staleness`` versions are dropped before the learner sees
  them (counted in ``rollout_fragments_dropped_stale``).  PPO stays
  correct off-policy through its ``action_logp`` importance ratios;
  IMPALA's V-trace absorbs the staleness natively.
- **Dead-worker tolerance** — a failed fragment future feeds the
  WorkerSet's existing ``_count_failure``/restore path (strike counting,
  actor replacement, weight re-seed from the current version's ref); the
  dead handle's queued fragments are abandoned, never delivered, so
  episode returns are counted at most once (docs/FAULT_TOLERANCE.md).

Observability: ``rollout_fragments_total`` / ``rollout_steps_total``
(Meters — locally aggregated, no per-fragment KV round trip),
``rollout_queue_depth`` gauge, ``rollout_weight_version_lag`` histogram,
``rollout_worker_idle_frac`` gauge, plus ``rollout_wait`` /
``rollout_publish_weights`` profiling spans.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, NamedTuple, Optional

import ray_tpu
from ray_tpu.parallel.flow import CancellationToken, Window


class Fragment(NamedTuple):
    """One consumed rollout fragment."""

    worker_index: int
    batch: Any                     # SampleBatch (gae) or time-major dict
    episode_returns: List[float]
    weights_version: int           # version the fragment was acted under
    env_steps: int
    info: Dict[str, Any]           # produce_start/end, idle_s, busy_s


class _Pending(NamedTuple):
    future: Any
    worker: Any                    # handle at dispatch time
    worker_index: int
    dispatched_at: float


def _stream_metrics():
    """Lazy metric handles (internal_kv needs a connected driver)."""
    from ray_tpu.util.metrics import Gauge, Histogram, Meter

    return {
        "fragments": Meter("rollout_fragments_total",
                           "rollout fragments consumed by the learner"),
        "steps": Meter("rollout_steps_total",
                       "env steps consumed through the rollout plane"),
        "stale": Meter("rollout_fragments_dropped_stale",
                       "fragments dropped by the weight-staleness bound"),
        "depth": Gauge("rollout_queue_depth",
                       "fragment futures in flight across all workers"),
        "idle": Gauge("rollout_worker_idle_frac",
                      "fraction of worker wall time spent not sampling"),
        "lag": Histogram("rollout_weight_version_lag",
                         "published version minus consumed fragment's "
                         "version", boundaries=(0.5, 1.5, 2.5, 4.5, 8.5)),
    }


class SampleStream:
    """Bounded streaming fragment consumer over a WorkerSet.

    ``kind`` selects the fragment shape (``"gae"`` for PPO's flat
    SampleBatch with advantages, ``"timemajor"`` for IMPALA's V-trace
    tensors).  Call :meth:`publish_weights` once before the first
    :meth:`next_fragment` so every worker has version >= 1 weights before
    any sample dispatch.

    Not thread-safe: one consumer thread owns a stream."""

    def __init__(self, workers, kind: str = "gae",
                 max_in_flight_per_worker: int = 2,
                 max_weight_staleness: Optional[int] = None,
                 export_metrics: bool = True):
        if max_in_flight_per_worker < 1:
            raise ValueError("max_in_flight_per_worker must be >= 1, got "
                             f"{max_in_flight_per_worker}")
        self.workers = workers
        self.kind = kind
        self.depth = int(max_in_flight_per_worker)
        self.max_weight_staleness = max_weight_staleness
        self._windows: Dict[int, Window] = {
            i: Window(self.depth)
            for i in range(len(workers.workers))
        }
        # One flow cancellation token governs the stream's lifetime: the
        # owner (or a supervisor's restart hook) cancels it once and every
        # in-flight window drains (docs/FAULT_TOLERANCE.md).
        self.token = CancellationToken()
        self.token.on_cancel(self._drop_all_windows)
        # --- stats (driver-local; stats() snapshots them) ---
        self._t0 = time.monotonic()
        self.fragments_consumed = 0
        self.steps_consumed = 0
        self.stale_dropped = 0
        self.failures_seen = 0
        self._lag_sum = 0
        self._lag_max = 0
        self._lag_hist: Dict[int, int] = {}
        self._idle_s = 0.0
        self._busy_s = 0.0
        self._wait_s = 0.0
        self._metrics = None
        if export_metrics:
            try:
                self._metrics = _stream_metrics()
            except Exception:
                self._metrics = None
        # One distributed trace per stream lifetime: every fragment
        # dispatch and rollout_* span joins it, so a whole rollout run
        # assembles into a single cross-process timeline.
        self.trace_ctx = None
        try:
            from ray_tpu import observability as obs

            if obs.enabled():
                self.trace_ctx = obs.get_context() or obs.mint_context()
        except Exception:
            pass

    # ---- weights ---------------------------------------------------------
    @property
    def weights_version(self) -> int:
        return self.workers.weights_version

    def publish_weights(self, params) -> int:
        """One put per version + async fan-out (see module docstring)."""
        t0 = time.perf_counter()
        version = self.workers.broadcast_weights_async(params)
        from ray_tpu._private import profiling

        profiling.record_span("rollout_publish_weights", t0,
                              time.perf_counter(), version=version,
                              _trace_ctx=self.trace_ctx)
        return version

    # ---- production ------------------------------------------------------
    def _refill(self) -> None:
        """Top every healthy worker's window up to the in-flight cap."""
        ctx = None
        if self.trace_ctx is not None:
            from ray_tpu import observability as obs

            ctx = obs.use_context(self.trace_ctx)
            ctx.__enter__()
        try:
            for i, w in enumerate(self.workers.workers):
                win = self._windows[i]
                while not win.full:
                    fut = w.sample_fragment.remote(self.kind)
                    win.append(_Pending(fut, w, i, time.monotonic()))
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)

    def _drop_window(self, i: int) -> None:
        """Abandon a dead handle's queued fragments: cancel what never
        started; results that do land are simply never consumed — the
        at-most-once episode-return accounting."""
        for p in self._windows[i].clear():
            try:
                ray_tpu.cancel(p.future)
            except Exception:
                pass

    def _drop_all_windows(self) -> None:
        for i in list(self._windows):
            self._drop_window(i)

    @property
    def inflight(self) -> int:
        return sum(len(w) for w in self._windows.values())

    def next_fragment(self, timeout: Optional[float] = None
                      ) -> Optional[Fragment]:
        """Block until the next fragment lands (refilling windows so
        production never drains), apply the staleness gate, and return it.
        Returns None when ``timeout`` elapses with nothing consumable."""
        if self.token.cancelled:
            raise RuntimeError("SampleStream is closed")
        deadline = None if timeout is None else time.monotonic() + timeout
        t_wait0 = time.perf_counter()
        while True:
            self._refill()
            pendings = [p for win in self._windows.values() for p in win]
            if not pendings:
                return None  # no workers at all
            ready, _ = ray_tpu.wait([p.future for p in pendings],
                                    num_returns=1, timeout=1.0)
            if not ready:
                if deadline is not None and time.monotonic() > deadline:
                    return None
                continue
            fut = ready[0]
            pend = next(p for p in pendings if p.future is fut)
            win = self._windows[pend.worker_index]
            try:
                win.remove(pend)
            except ValueError:
                continue  # window was dropped by a concurrent failure
            try:
                batch, completed, info = ray_tpu.get(fut)
            except ray_tpu.exceptions.RayTpuError:
                # Feed the existing FT manager (strike counting, actor
                # replacement past the budget, weight restore), abandon
                # the dead handle's window, and keep streaming.  This
                # includes RpcTimeoutError: a worker whose RPC edge blew
                # its deadline is treated exactly like a dead worker —
                # struck and replaced — instead of stalling the stream
                # waiting on a reply that may never come.
                self.failures_seen += 1
                self._drop_window(pend.worker_index)
                self.workers.report_failure_index(pend.worker_index)
                continue
            version = int(info.get("weights_version", 0))
            lag = self.weights_version - version
            self._idle_s += float(info.get("idle_s", 0.0))
            self._busy_s += float(info.get("busy_s", 0.0))
            if self.max_weight_staleness is not None and \
                    lag > self.max_weight_staleness:
                self.stale_dropped += 1
                if self._metrics is not None:
                    try:
                        self._metrics["stale"].mark()
                    except Exception:
                        pass
                continue  # refilled next loop; newer weights are queued
            t1 = time.perf_counter()
            self._wait_s += t1 - t_wait0
            from ray_tpu._private import profiling

            profiling.record_span("rollout_wait", t_wait0, t1,
                                  worker=pend.worker_index, lag=lag,
                                  _trace_ctx=self.trace_ctx)
            steps = int(info.get("env_steps", 0))
            self.fragments_consumed += 1
            self.steps_consumed += steps
            self._lag_sum += max(0, lag)
            self._lag_max = max(self._lag_max, lag)
            self._lag_hist[lag] = self._lag_hist.get(lag, 0) + 1
            if self._metrics is not None:
                try:
                    self._metrics["fragments"].mark()
                    self._metrics["steps"].mark(steps)
                    self._metrics["depth"].set(float(self.inflight))
                    self._metrics["lag"].observe(float(lag))
                    self._metrics["idle"].set(self.worker_idle_frac())
                except Exception:
                    pass
            return Fragment(pend.worker_index, batch, completed, version,
                            steps, info)

    # ---- observability ---------------------------------------------------
    def worker_idle_frac(self) -> float:
        total = self._idle_s + self._busy_s
        return self._idle_s / total if total > 0 else 0.0

    def stats(self) -> Dict[str, Any]:
        dt = time.monotonic() - self._t0
        n = max(1, self.fragments_consumed)
        return {
            "fragments_consumed": self.fragments_consumed,
            "steps_consumed": self.steps_consumed,
            "fragments_per_s": self.fragments_consumed / dt if dt else 0.0,
            "steps_per_s": self.steps_consumed / dt if dt else 0.0,
            "stale_dropped": self.stale_dropped,
            "failures_seen": self.failures_seen,
            "weights_version": self.weights_version,
            "weight_lag_mean": self._lag_sum / n,
            "weight_lag_max": self._lag_max,
            "weight_lag_hist": dict(sorted(self._lag_hist.items())),
            "worker_idle_frac": self.worker_idle_frac(),
            "driver_wait_s": self._wait_s,
            "inflight": self.inflight,
        }

    def close(self) -> None:
        """Abandon all in-flight fragments (the workers' queued fragments
        finish and are garbage-collected unseen).  One token cancel — the
        window drop rides the flow token's on_cancel hook."""
        if self.token.cancelled:
            return
        self.token.cancel()
        if self._metrics is not None:
            for m in self._metrics.values():
                flush = getattr(m, "flush", None)
                if flush is not None:
                    try:
                        flush()
                    except Exception:
                        pass

    def __enter__(self) -> "SampleStream":
        return self

    def __exit__(self, exc_type, exc_val, tb) -> None:
        self.close()
