"""GAE advantage estimation (reference: rllib/evaluation/postprocessing.py
compute_advantages/compute_gae_for_sample_batch).  Both a numpy version (CPU
rollout actors) and a jax version (inside the jitted Anakin train step)."""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ADVANTAGES,
    DONES,
    REWARDS,
    SampleBatch,
    VALUE_TARGETS,
    VF_PREDS,
)


def compute_gae(batch: SampleBatch, last_value: float, gamma: float = 0.99,
                lambda_: float = 0.95) -> SampleBatch:
    """In-place GAE over a time-ordered fragment (dones mark resets)."""
    rewards = batch[REWARDS].astype(np.float64)
    values = batch[VF_PREDS].astype(np.float64)
    dones = batch[DONES].astype(np.float64)
    n = len(rewards)
    adv = np.zeros(n)
    last_gae = 0.0
    next_value = last_value
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lambda_ * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    batch[ADVANTAGES] = adv.astype(np.float32)
    batch[VALUE_TARGETS] = (adv + values).astype(np.float32)
    return batch


def gae_jax(rewards, values, dones, last_value, gamma: float = 0.99,
            lambda_: float = 0.95):
    """rewards/values/dones: [T, N] time-major. Returns (advantages,
    value_targets) [T, N].  Pure scan — runs inside jit on device."""
    import jax
    import jax.numpy as jnp

    nonterminal = 1.0 - dones.astype(jnp.float32)

    def step(carry, xs):
        last_gae, next_value = carry
        r, v, nt = xs
        delta = r + gamma * next_value * nt - v
        gae = delta + gamma * lambda_ * nt * last_gae
        return (gae, v), gae

    (_, _), adv_rev = jax.lax.scan(
        step, (jnp.zeros_like(last_value), last_value),
        (rewards[::-1], values[::-1], nonterminal[::-1]))
    adv = adv_rev[::-1]
    return adv, adv + values
