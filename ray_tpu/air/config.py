"""Run/scaling/failure/checkpoint configs (reference: python/ray/air/config.py
ScalingConfig :79, FailureConfig :483, CheckpointConfig :542, RunConfig :670).

TPU-specific: ScalingConfig speaks in *hosts* and *chips* and carries a
MeshSpec — a "worker" is one process per TPU host and the real parallelism
layout lives in the mesh axes, not in worker count.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    """num_workers = processes (1 per TPU host). use_tpu selects the chip
    resource; chips_per_worker reserves them; mesh describes the logical
    parallelism over ALL chips of the group."""

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    mesh: Optional[MeshSpec] = None
    placement_strategy: str = "PACK"

    # Reference-compat alias (trainer_resources etc. intentionally dropped).
    @property
    def num_tpus_per_worker(self) -> int:
        return self.chips_per_worker

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu and self.chips_per_worker:
            res["TPU"] = float(self.chips_per_worker)
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0  # -1 = unlimited trial retries


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # local dir (cloud URI round-2)
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    # Mirror the experiment dir to durable storage after each state save
    # (reference: SyncConfig/Syncer, python/ray/tune/syncer.py).
    sync_config: Optional["SyncConfig"] = None


@dataclasses.dataclass
class SyncConfig:
    upload_dir: Optional[str] = None
    sync_period_s: float = 0.0  # 0 = sync on every experiment-state save
