"""Run/scaling/failure/checkpoint configs (reference: python/ray/air/config.py
ScalingConfig :79, FailureConfig :483, CheckpointConfig :542, RunConfig :670).

TPU-specific: ScalingConfig speaks in *hosts* and *chips* and carries a
MeshSpec — a "worker" is one process per TPU host and the real parallelism
layout lives in the mesh axes, not in worker count.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Union

from ray_tpu.parallel.mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    """num_workers = processes (1 per TPU host). use_tpu selects the chip
    resource; chips_per_worker reserves them; mesh describes the logical
    parallelism over ALL chips of the group.

    ``num_workers`` may be an ``(min, max)`` tuple for an *elastic* gang:
    BackendExecutor starts as many workers as the cluster can place right
    now (probing max→min) and never below min."""

    num_workers: Union[int, Tuple[int, int]] = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    mesh: Optional[MeshSpec] = None
    placement_strategy: str = "PACK"

    def worker_range(self) -> Tuple[int, int]:
        """(min, max) worker count — a fixed ``num_workers=n`` is the
        degenerate range (n, n)."""
        nw = self.num_workers
        if isinstance(nw, int):
            if nw < 1:
                raise ValueError(f"num_workers must be >= 1, got {nw}")
            return (nw, nw)
        lo, hi = int(nw[0]), int(nw[1])
        if not 1 <= lo <= hi:
            raise ValueError(f"bad elastic num_workers range {nw!r}")
        return (lo, hi)

    @property
    def min_workers(self) -> int:
        return self.worker_range()[0]

    @property
    def max_workers(self) -> int:
        return self.worker_range()[1]

    # Reference-compat alias (trainer_resources etc. intentionally dropped).
    @property
    def num_tpus_per_worker(self) -> int:
        return self.chips_per_worker

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu and self.chips_per_worker:
            res["TPU"] = float(self.chips_per_worker)
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0  # -1 = unlimited trial retries


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # local dir (cloud URI round-2)
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    # Mirror the experiment dir to durable storage after each state save
    # (reference: SyncConfig/Syncer, python/ray/tune/syncer.py).
    sync_config: Optional["SyncConfig"] = None


@dataclasses.dataclass
class SyncConfig:
    upload_dir: Optional[str] = None
    sync_period_s: float = 0.0  # 0 = sync on every experiment-state save
