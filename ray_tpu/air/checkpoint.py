"""Universal Checkpoint: dict ⇄ directory, with native jax-pytree support.

Reference: ray.air.Checkpoint (python/ray/air/checkpoint.py:63) — the
dict/directory/URI-interconvertible checkpoint that flows worker → driver →
tune → storage.  The TPU-native addition is first-class jax pytrees:
`from_pytree/to_pytree` store arrays via flax.serialization (msgpack) so
device arrays round-trip without pickling device buffers; large trees can
use orbax under the same interface.
"""
from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional

_PYTREE_FILE = "pytree.msgpack"
_DICT_FILE = "checkpoint.pkl"


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None):
        if (data is None) == (directory is None):
            raise ValueError("exactly one of data / directory required")
        self._data = data
        self._dir = directory

    # ---- constructors ----
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=path)

    @classmethod
    def from_pytree(cls, tree: Any, extra: Optional[dict] = None) -> "Checkpoint":
        """Store a jax/flax pytree (host-transferred, msgpack-serialized)."""
        import jax
        from flax import serialization

        host_tree = jax.device_get(tree)
        return cls(data={"__pytree__": serialization.to_bytes(host_tree),
                         "__template__": pickle.dumps(
                             jax.tree_util.tree_map(lambda x: None, host_tree)),
                         **(extra or {})})

    # ---- accessors ----
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        path = os.path.join(self._dir, _DICT_FILE)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        out: Dict[str, Any] = {}
        pt = os.path.join(self._dir, _PYTREE_FILE)
        if os.path.exists(pt):
            with open(pt, "rb") as f:
                out["__pytree__"] = f.read()
        return out

    def to_pytree(self, target: Any = None) -> Any:
        """Restore the stored pytree; `target` provides the structure (else
        the stored structure template is used)."""
        from flax import serialization

        data = self.to_dict()
        blob = data["__pytree__"]
        if target is None:
            target = pickle.loads(data["__template__"])
        return serialization.from_bytes(target, blob)

    def extra(self) -> Dict[str, Any]:
        return {k: v for k, v in self.to_dict().items()
                if k not in ("__pytree__", "__template__")}

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(tempfile.gettempdir(),
                                    f"rtpu_ckpt_{uuid.uuid4().hex[:8]}")
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
        else:
            with open(os.path.join(path, _DICT_FILE), "wb") as f:
                pickle.dump(self._data, f)
        return path

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._dir}"
        return f"Checkpoint({kind})"
