"""Universal Checkpoint: dict ⇄ directory, with native jax-pytree support.

Reference: ray.air.Checkpoint (python/ray/air/checkpoint.py:63) — the
dict/directory/URI-interconvertible checkpoint that flows worker → driver →
tune → storage.  The TPU-native addition is first-class jax pytrees:
`from_pytree/to_pytree` store arrays via flax.serialization (msgpack) so
device arrays round-trip without pickling device buffers; large trees can
use orbax under the same interface.
"""
from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional

_PYTREE_FILE = "pytree.msgpack"
_DICT_FILE = "checkpoint.pkl"


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None):
        if (data is None) == (directory is None):
            raise ValueError("exactly one of data / directory required")
        self._data = data
        self._dir = directory

    # ---- constructors ----
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=path)

    @classmethod
    def from_pytree(cls, tree: Any, extra: Optional[dict] = None) -> "Checkpoint":
        """Store a jax/flax pytree (host-transferred, msgpack-serialized)."""
        import jax
        from flax import serialization

        host_tree = jax.device_get(tree)
        return cls(data={"__pytree__": serialization.to_bytes(host_tree),
                         "__template__": pickle.dumps(
                             jax.tree_util.tree_map(lambda x: None, host_tree)),
                         **(extra or {})})

    @classmethod
    def from_sharded(cls, root: str, step: Optional[int] = None) -> "Checkpoint":
        """Open a committed step of a distributed sharded checkpoint store
        (``ray_tpu.checkpoint``): ``step=None`` means the latest committed
        manifest.  ``to_pytree`` reassembles the full global tree from the
        per-rank shards (resharded restore: pass rank/world via
        ``to_pytree_resharded``)."""
        return ShardedCheckpoint(root, step)

    # ---- accessors ----
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        path = os.path.join(self._dir, _DICT_FILE)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        out: Dict[str, Any] = {}
        pt = os.path.join(self._dir, _PYTREE_FILE)
        if os.path.exists(pt):
            with open(pt, "rb") as f:
                out["__pytree__"] = f.read()
            return out
        raise ValueError(
            f"checkpoint directory {self._dir!r} contains neither "
            f"{_DICT_FILE!r} nor {_PYTREE_FILE!r} — not a checkpoint "
            f"(was the directory partially written or already deleted?)")

    def to_pytree(self, target: Any = None) -> Any:
        """Restore the stored pytree; `target` provides the structure (else
        the stored structure template is used)."""
        from flax import serialization

        data = self.to_dict()
        blob = data["__pytree__"]
        if target is None:
            target = pickle.loads(data["__template__"])
        return serialization.from_bytes(target, blob)

    def extra(self) -> Dict[str, Any]:
        return {k: v for k, v in self.to_dict().items()
                if k not in ("__pytree__", "__template__")}

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(tempfile.gettempdir(),
                                    f"rtpu_ckpt_{uuid.uuid4().hex[:8]}")
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
        else:
            with open(os.path.join(path, _DICT_FILE), "wb") as f:
                pickle.dump(self._data, f)
        return path

    def delete(self) -> None:
        """Remove the checkpoint's on-disk footprint (no-op for in-memory
        dict checkpoints).  Used by CheckpointManager eviction so
        ``num_to_keep`` actually reclaims disk, not just list slots."""
        if self._dir is not None:
            shutil.rmtree(self._dir, ignore_errors=True)

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._dir}"
        return f"Checkpoint({kind})"


class ShardedCheckpoint(Checkpoint):
    """A committed step of a distributed sharded checkpoint store.

    Directory-backed on the step dir, but the authoritative reader is the
    manifest + chunk store: ``to_pytree`` reassembles global arrays from
    every rank's shards (``ray_tpu.checkpoint.restore``)."""

    def __init__(self, root: str, step: Optional[int] = None):
        from ray_tpu.checkpoint import manifest as mf

        if step is None:
            step = mf.latest_committed_step(root)
            if step is None:
                raise FileNotFoundError(
                    f"no committed checkpoint manifest under {root!r}")
        super().__init__(directory=mf.step_dir(root, step))
        self.root = root
        self.step = int(step)

    def manifest(self) -> Dict[str, Any]:
        from ray_tpu.checkpoint import manifest as mf

        return mf.read_manifest(self.root, self.step)

    def to_dict(self) -> Dict[str, Any]:
        m = self.manifest()
        if m.get("kind") == "dict":
            return super().to_dict()
        return {"__sharded__": True, "root": self.root, "step": self.step,
                **m.get("meta", {})}

    def to_pytree(self, target: Any = None) -> Any:
        from ray_tpu.checkpoint.restore import restore_tree

        return restore_tree(self.root, step=self.step, target=target)

    def to_pytree_resharded(self, target: Any = None, rank: int = 0,
                            world_size: int = 1, index_fn=None) -> Any:
        """Restore this rank's reshard of the checkpoint (an N-rank save
        onto an M-rank gang).  Default resharding is the even axis-0
        split; pass ``index_fn`` for custom layouts."""
        from ray_tpu.checkpoint.restore import restore_tree
        from ray_tpu.checkpoint.tree import axis0_restore_index

        if index_fn is None and world_size > 1:
            index_fn = axis0_restore_index(rank, world_size)
        return restore_tree(self.root, step=self.step, target=target,
                            index_fn=index_fn)

    def extra(self) -> Dict[str, Any]:
        return dict(self.manifest().get("meta", {}))

    def delete(self) -> None:
        """Evict this step: remove its dir, then sweep chunks no other
        committed manifest references."""
        from ray_tpu.checkpoint import manifest as mf

        mf.delete_step(self.root, self.step)
        try:
            mf.gc_chunks(self.root)
        except Exception:
            pass

    def __repr__(self):
        return f"ShardedCheckpoint(root={self.root!r}, step={self.step})"
