"""Top-K checkpoint retention (reference: air/_internal/checkpoint_manager.py
:233 — keep best K by score attribute, delete the rest).

Two behaviors beyond the in-memory list:

- **Eviction deletes from disk.**  ``num_to_keep`` used to only truncate
  the entry list, leaking every evicted directory-backed checkpoint;
  evicted entries now have their on-disk footprint removed via
  ``Checkpoint.delete()`` (sharded steps additionally sweep
  now-unreferenced chunks).
- **Durable latest-pointer.**  With a ``storage_path``, every registered
  checkpoint is persisted into the sharded store's commit protocol
  (dict payload + atomic manifest), so ``discover_latest_checkpoint``
  recovers the latest checkpoint after a full driver process restart —
  the in-memory ``latest`` is a cache, not the source of truth.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig


def discover_latest_checkpoint(storage_path: str) -> Optional[Checkpoint]:
    """The latest COMMITTED checkpoint under ``storage_path`` (manifest
    discovery — survives driver restarts; partial saves are invisible).
    Returns None when the store holds no committed step."""
    from ray_tpu.checkpoint import manifest as mf

    step = mf.latest_committed_step(storage_path)
    if step is None:
        return None
    return Checkpoint.from_sharded(storage_path, step)


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None,
                 storage_path: Optional[str] = None):
        self.config = config or CheckpointConfig()
        self.storage_path = storage_path
        # (score, seq, checkpoint, metrics)
        self._entries: List[Tuple[float, int, Checkpoint, dict]] = []
        # Seed the sequence from the store: register() uses it as the
        # persisted step number, and a fresh manager (elastic retry or
        # driver restart) starting back at 0 would overwrite committed
        # step dirs while manifest discovery kept resuming from the
        # stale, highest-numbered pre-restart checkpoint.
        self._seq = 0
        if storage_path:
            try:
                from ray_tpu.checkpoint import manifest as mf

                self._seq = mf.latest_committed_step(storage_path) or 0
            except Exception:
                pass
        self.latest: Optional[Checkpoint] = None

    def _persist(self, checkpoint: Checkpoint, metrics: dict,
                 step: int) -> Checkpoint:
        """Spill a driver-side checkpoint into the sharded store (commit
        protocol), returning the durable handle.  Sharded checkpoints
        already live in a store — they pass through."""
        from ray_tpu.air.checkpoint import ShardedCheckpoint
        from ray_tpu.checkpoint.saver import persist_dict_checkpoint

        if isinstance(checkpoint, ShardedCheckpoint):
            return checkpoint
        meta = {k: v for k, v in metrics.items()
                if isinstance(v, (int, float, str, bool))}
        persist_dict_checkpoint(self.storage_path, step,
                                checkpoint.to_dict(), meta=meta)
        return Checkpoint.from_sharded(self.storage_path, step)

    def register(self, checkpoint: Checkpoint, metrics: dict,
                 step: Optional[int] = None):
        self._seq += 1
        if self.storage_path:
            try:
                checkpoint = self._persist(
                    checkpoint, metrics,
                    self._seq if step is None else step)
            except Exception:
                pass  # durability is best-effort; in-memory flow continues
        self.latest = checkpoint
        attr = self.config.checkpoint_score_attribute
        score = float(metrics.get(attr, self._seq)) if attr else float(self._seq)
        if self.config.checkpoint_score_order == "min":
            score = -score
        self._entries.append((score, self._seq, checkpoint, dict(metrics)))
        self._entries.sort(key=lambda e: (e[0], e[1]))
        k = self.config.num_to_keep
        if k is not None and len(self._entries) > k:
            evicted, self._entries = self._entries[:-k], self._entries[-k:]
            kept = {id(e[2]) for e in self._entries}
            for _, _, ckpt, _ in evicted:
                # Never delete the resume source out from under a restart.
                if ckpt is self.latest or id(ckpt) in kept:
                    continue
                try:
                    ckpt.delete()
                except Exception:
                    pass

    @property
    def best(self) -> Optional[Checkpoint]:
        return self._entries[-1][2] if self._entries else None

    @property
    def best_metrics(self) -> Optional[dict]:
        return self._entries[-1][3] if self._entries else None

    def checkpoints(self) -> List[Checkpoint]:
        return [e[2] for e in self._entries]
