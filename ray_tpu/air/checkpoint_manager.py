"""Top-K checkpoint retention (reference: air/_internal/checkpoint_manager.py
:233 — keep best K by score attribute, delete the rest)."""
from __future__ import annotations

from typing import List, Optional, Tuple

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        # (score, seq, checkpoint, metrics)
        self._entries: List[Tuple[float, int, Checkpoint, dict]] = []
        self._seq = 0
        self.latest: Optional[Checkpoint] = None

    def register(self, checkpoint: Checkpoint, metrics: dict):
        self._seq += 1
        self.latest = checkpoint
        attr = self.config.checkpoint_score_attribute
        score = float(metrics.get(attr, self._seq)) if attr else float(self._seq)
        if self.config.checkpoint_score_order == "min":
            score = -score
        self._entries.append((score, self._seq, checkpoint, dict(metrics)))
        self._entries.sort(key=lambda e: (e[0], e[1]))
        k = self.config.num_to_keep
        if k is not None and len(self._entries) > k:
            self._entries = self._entries[-k:]

    @property
    def best(self) -> Optional[Checkpoint]:
        return self._entries[-1][2] if self._entries else None

    @property
    def best_metrics(self) -> Optional[dict]:
        return self._entries[-1][3] if self._entries else None

    def checkpoints(self) -> List[Checkpoint]:
        return [e[2] for e in self._entries]
