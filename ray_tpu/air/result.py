"""Result object returned by trainers/tuner (reference: ray.air.Result)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    metrics_history: Optional[List[Dict[str, Any]]] = None

    @property
    def metrics_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.metrics_history or [self.metrics])
