"""Worker-facing training session facade (reference: python/ray/air/
session.py:41 — report, get_checkpoint, get_dataset_shard, rank queries).

The active session is installed per-process by the Train worker loop or the
Tune function-trainable wrapper; the same `report()` works in both, exactly
like the reference.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint

_session = threading.local()


class _Session:
    def __init__(self, report_fn, world_rank=0, world_size=1,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 trial_info: Optional[dict] = None,
                 storage_path: Optional[str] = None):
        self.report_fn = report_fn
        self.world_rank = world_rank
        self.world_size = world_size
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.trial_info = trial_info or {}
        self.storage_path = storage_path


def init_session(**kw):
    _session.value = _Session(**kw)


def shutdown_session():
    _session.value = None


def _get() -> Optional[_Session]:
    return getattr(_session, "value", None)


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    s = _get()
    if s is None:
        raise RuntimeError("session.report() called outside a training session")
    s.report_fn(dict(metrics), checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get()
    return s.loaded_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    s = _get()
    if s is None:
        raise RuntimeError("no active session")
    return s.dataset_shards.get(name)


def get_world_rank() -> int:
    s = _get()
    return s.world_rank if s else 0


def get_world_size() -> int:
    s = _get()
    return s.world_size if s else 1


def get_trial_name() -> Optional[str]:
    s = _get()
    return s.trial_info.get("name") if s else None


def get_storage_path() -> Optional[str]:
    """The experiment's checkpoint store root (RunConfig.storage_path),
    exported to every training worker — rank loops use it to save per-rank
    shards directly (``ray_tpu.checkpoint.ShardWriter(get_storage_path(),
    get_world_rank(), get_world_size())``) instead of shipping full state
    through ``session.report``."""
    import os

    s = _get()
    if s is not None and s.storage_path:
        return s.storage_path
    return os.environ.get("RTPU_CHECKPOINT_ROOT") or None


def sharded_writer():
    """Convenience: a ``ShardWriter`` for this worker's (rank, world) into
    the session's storage path.  Raises when no storage path is set."""
    root = get_storage_path()
    if not root:
        raise RuntimeError(
            "session.sharded_writer() needs RunConfig.storage_path (or "
            "RTPU_CHECKPOINT_ROOT) to be set")
    from ray_tpu.checkpoint.saver import ShardWriter

    return ShardWriter(root, get_world_rank(), get_world_size())
