"""AIR-equivalent glue: configs, Checkpoint, session, Result.

Reference: python/ray/air/ (Checkpoint air/checkpoint.py:63, configs
air/config.py:79-670, session air/session.py:41)."""
from ray_tpu.air.checkpoint import Checkpoint, ShardedCheckpoint  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    SyncConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result  # noqa: F401
from ray_tpu.air import session  # noqa: F401
