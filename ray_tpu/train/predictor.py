"""Predictors + distributed batch inference.

Reference: python/ray/train/predictor.py:38 (Predictor.from_checkpoint /
predict contract) and python/ray/train/batch_predictor.py:23
(BatchPredictor.predict mapping a checkpointed model over a Dataset with
actor-pooled workers).  TPU redesign: the per-batch compute is one jitted
apply on device-resident params — batches stream through
Dataset.map_batches so each worker process jits once and reuses the
compiled kernel for every block it serves.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """One-model inference over numpy batches."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kw) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Jitted apply over checkpointed params.

    ``apply_fn(params, inputs) -> outputs``; inputs are taken from the
    batch's ``input_column`` (default: the single column present).
    """

    def __init__(self, params: Any, apply_fn: Callable,
                 input_column: Optional[str] = None,
                 output_column: str = "predictions"):
        import jax

        self._params = params
        self._apply = jax.jit(apply_fn)
        self._in = input_column
        self._out = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *, apply_fn: Callable,
                        input_column: Optional[str] = None,
                        output_column: str = "predictions"
                        ) -> "JaxPredictor":
        tree = checkpoint.to_pytree()
        params = tree.get("params", tree) if isinstance(tree, dict) else tree
        return cls(params, apply_fn, input_column, output_column)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        import jax

        col = self._in
        if col is None:
            if len(batch) != 1:
                raise ValueError(
                    f"batch has columns {sorted(batch)}; pass input_column")
            col = next(iter(batch))
        out = self._apply(self._params, batch[col])
        return {**batch, self._out: np.asarray(jax.device_get(out))}


class BatchPredictor:
    """Map a checkpointed predictor over a Dataset (reference:
    batch_predictor.py:23)."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls, **predictor_kw):
        self._ckpt = checkpoint
        self._cls = predictor_cls
        self._kw = predictor_kw

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls,
                        **predictor_kw) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kw)

    def predict(self, dataset, *, keep_columns=None):
        """Returns a new Dataset with the prediction column appended.
        Each block task rebuilds the predictor lazily in its worker (jit
        once per process) and serves every block scheduled there."""
        import uuid

        import ray_tpu

        # Put the checkpoint in the object store ONCE — capturing the raw
        # dict in the closure would re-serialize the full param tree into
        # the store for every block task of the fan-out.
        ckpt_ref = ray_tpu.put(self._ckpt.to_dict())
        predictor_cls, kw = self._cls, self._kw
        # Stable token across the fan-out: every block task of this predict
        # call shares one worker-side predictor (one jit compile per
        # process), keyed by value rather than closure identity — the
        # closure deserializes fresh per task.
        token = uuid.uuid4().hex

        def _infer(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
            import ray_tpu as rt
            import ray_tpu.train.predictor as mod

            cache = getattr(mod, "_predictor_cache", None)
            if cache is None:
                cache = {}
                mod._predictor_cache = cache
            predictor = cache.get(token)
            if predictor is None:
                predictor = predictor_cls.from_checkpoint(
                    Checkpoint.from_dict(rt.get(ckpt_ref)), **kw)
                cache.clear()  # one live predictor per worker is plenty
                cache[token] = predictor
            out = predictor.predict(batch)
            if keep_columns is not None:
                keep = set(keep_columns) | {kw.get("output_column",
                                                   "predictions")}
                out = {k: v for k, v in out.items() if k in keep}
            return out

        return dataset.map_batches(_infer, batch_format="numpy")
