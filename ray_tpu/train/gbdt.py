"""XGBoost / LightGBM trainers (reference:
python/ray/train/xgboost/xgboost_trainer.py, lightgbm/, gbdt_trainer.py).

Both libraries speak the sklearn fit/predict/score contract, so the
trainers are thin subclasses of SklearnTrainer that construct the
library's sklearn-API estimator.  Neither library ships in this image
(no package egress), so construction is import-gated with an actionable
error instead of failing deep inside a fit worker.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.train.sklearn import SklearnTrainer


def _require(module_name: str, trainer_name: str):
    try:
        return __import__(module_name)
    except ImportError:
        raise ImportError(
            f"{trainer_name} requires the '{module_name}' package, which "
            f"is not installed in this environment. Install it (pip "
            f"install {module_name}) or use SklearnTrainer with e.g. "
            f"sklearn.ensemble.HistGradientBoostingRegressor — the "
            f"in-tree gradient-boosting estimator with the same "
            f"contract.") from None


class XGBoostTrainer(SklearnTrainer):
    """reference: XGBoostTrainer (train/xgboost/xgboost_trainer.py)."""

    def __init__(self, *, params: Optional[Dict[str, Any]] = None,
                 objective: str = "reg:squarederror",
                 datasets: Dict[str, Any], label_column: Optional[str] = None,
                 **kwargs):
        xgb = _require("xgboost", "XGBoostTrainer")
        params = dict(params or {})
        cls = (xgb.XGBClassifier if objective.startswith(("binary", "multi"))
               else xgb.XGBRegressor)
        super().__init__(estimator=cls(objective=objective, **params),
                         datasets=datasets, label_column=label_column,
                         **kwargs)


class LightGBMTrainer(SklearnTrainer):
    """reference: LightGBMTrainer (train/lightgbm/lightgbm_trainer.py)."""

    def __init__(self, *, params: Optional[Dict[str, Any]] = None,
                 objective: str = "regression",
                 datasets: Dict[str, Any], label_column: Optional[str] = None,
                 **kwargs):
        lgb = _require("lightgbm", "LightGBMTrainer")
        params = dict(params or {})
        # LightGBM's classification objectives and their aliases (the
        # library accepts several names per task).
        classification = {"binary", "multiclass", "multiclassova",
                          "multiclass_ova", "ova", "ovr",
                          "cross_entropy", "xentropy",
                          "cross_entropy_lambda", "xentlambda"}
        cls = (lgb.LGBMClassifier if objective in classification
               else lgb.LGBMRegressor)
        super().__init__(estimator=cls(objective=objective, **params),
                         datasets=datasets, label_column=label_column,
                         **kwargs)
