"""BaseTrainer / DataParallelTrainer.

Reference: python/ray/train/base_trainer.py:39 (fit :344) and
data_parallel_trainer.py:56 (training_loop :347).  One deliberate
divergence: the reference routes EVERY fit() through Tune
(base_trainer.py:344-363 constructs a Tuner even for a single run); here
fit() drives the executor directly and `as_trainable()` provides the Tune
integration — same capability, less layering in the common path.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.checkpoint_manager import (
    CheckpointManager,
    discover_latest_checkpoint,
)
from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train._internal.backend_executor import (
    BackendExecutor,
    TrainingWorkerError,
)


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def _discover_checkpoint(self) -> Optional[Checkpoint]:
        """Latest COMMITTED checkpoint manifest under storage_path — the
        durable resume pointer.  Survives a full driver process restart
        (the in-memory ``_latest_checkpoint`` does not), and two-phase
        commit guarantees it never names a partially-written save."""
        storage = self.run_config.storage_path
        if not storage:
            return None
        try:
            return discover_latest_checkpoint(storage)
        except Exception:
            return None

    def fit(self) -> Result:
        import time

        failure = self.run_config.failure_config or FailureConfig()
        failure_budget = failure.max_failures \
            if failure.max_failures >= 0 else 10**9
        # The gloo TCP abort (mesh_group.is_transport_abort) is an
        # environmental hiccup the backend already retries in place; if
        # one still escapes, the rebuild is charged HERE, not against the
        # user's FailureConfig — tests no longer need per-test headroom.
        transport_budget = 2
        failures = transports = 0
        last_error: Optional[BaseException] = None
        checkpoint = self.resume_from_checkpoint
        if checkpoint is None:
            # Fresh driver process against an existing experiment dir:
            # resume where the last committed checkpoint left off.
            checkpoint = self._discover_checkpoint()
        attempt = 0
        while True:
            # Incarnation index: the executor exports it to the gang so
            # chaos kill schedules can target exactly one generation, and
            # operators can tell restarts apart in worker logs.
            self._elastic_generation = attempt
            if attempt:
                # Exponential backoff between elastic restarts — a
                # crash-looping gang (bad host, leaked coordinator port)
                # must not hot-spin placement groups.
                time.sleep(min(0.2 * 2 ** (attempt - 1), 10.0))
            try:
                return self._run(checkpoint)
            except TrainingWorkerError as e:
                last_error = e
                if getattr(e, "transport_abort", False):
                    transports += 1
                    if transports > transport_budget:
                        break
                else:
                    failures += 1
                    if failures > failure_budget:
                        break
                # Elastic restart resumes from the latest checkpoint: the
                # next _run() builds a FRESH executor + worker gang (new
                # processes re-run the jax.distributed rendezvous).  Disk
                # manifest discovery outranks the in-memory cache — with a
                # storage_path every registered checkpoint is committed
                # there, and workers may have sharded-saved past the last
                # driver-observed report.
                checkpoint = (self._discover_checkpoint()
                              or getattr(self, "_latest_checkpoint", None)
                              or checkpoint)
                try:
                    from ray_tpu.util.metrics import Counter

                    Counter("train_elastic_restarts_total",
                            "Train gang restarts after worker failure").inc()
                except Exception:
                    pass
            attempt += 1
        return Result(metrics={}, checkpoint=checkpoint, error=last_error)

    def _run(self, checkpoint: Optional[Checkpoint]) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Wrap for Tune: returns a function-trainable closing over self
        (reference: TrainTrainable wrapper, base_trainer.py:431)."""
        trainer = self

        def train_func(config: Dict[str, Any]):
            from ray_tpu.air import session

            t = trainer.with_updated_config(config)
            result = t.fit()
            if result.error:
                raise result.error
            session.report(result.metrics, checkpoint=result.checkpoint)

        return train_func

    def with_updated_config(self, config: Dict[str, Any]) -> "BaseTrainer":
        return self


class DataParallelTrainer(BaseTrainer):
    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or BackendConfig()
        self.datasets = datasets or {}

    def with_updated_config(self, config: Dict[str, Any]):
        import copy

        t = copy.copy(self)
        t.train_loop_config = {**self.train_loop_config, **config}
        return t

    def _run(self, checkpoint: Optional[Checkpoint]) -> Result:
        executor = BackendExecutor(
            self.backend_config, self.scaling_config,
            generation=getattr(self, "_elastic_generation", 0),
            storage_path=self.run_config.storage_path)
        ckpt_mgr = CheckpointManager(self.run_config.checkpoint_config,
                                     storage_path=self.run_config.storage_path)
        history = []
        final_metrics: Dict[str, Any] = {}
        try:
            executor.start()
            # Shard datasets over the size the executor actually got (the
            # elastic range may have landed below max_workers).
            shards = self._dataset_shards(executor.num_workers)
            executor.start_training(self.train_loop_per_worker,
                                    self.train_loop_config, checkpoint, shards)
            stop = self.run_config.stop or {}
            while True:
                results = executor.get_next_results()
                if results is None:
                    break
                # rank-0 metrics are canonical (all ranks report in lockstep).
                kind, metrics, ckpt = results[0]
                if kind != "report":
                    continue
                for _, _, c in results:
                    if c is not None:
                        ckpt_mgr.register(c, metrics)
                        self._latest_checkpoint = c
                final_metrics = metrics
                history.append(metrics)
                if any(metrics.get(k) is not None and metrics[k] >= v
                       for k, v in stop.items()):
                    break
        finally:
            executor.shutdown()
        return Result(metrics=final_metrics,
                      checkpoint=ckpt_mgr.latest or checkpoint,
                      metrics_history=history)

    def _dataset_shards(self, n: int):
        if not self.datasets:
            return None
        shards = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "split"):
                for i, piece in enumerate(ds.split(n, equal=True)):
                    shards[i][name] = piece
            else:
                for i in range(n):
                    shards[i][name] = ds
        return shards
