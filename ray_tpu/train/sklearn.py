"""SklearnTrainer + SklearnPredictor (reference:
python/ray/train/sklearn/sklearn_trainer.py — fit an sklearn-API
estimator on a Dataset in a remote worker, score it, and checkpoint the
pickled model; sklearn_predictor.py for batch inference).

The same `_fit_remote` path backs the gated XGBoost/LightGBM trainers
(train/gbdt.py): anything with the sklearn fit/predict/score contract
trains through here.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.result import Result
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train.predictor import Predictor

_MODEL_KEY = "_sklearn_model"


def _dataset_to_xy(ds, label_column: str):
    """Materialize a (possibly distributed) Dataset into X, y arrays —
    sklearn estimators are single-process, so the fit worker gathers."""
    rows = ds.take_all() if hasattr(ds, "take_all") else list(ds)
    if not rows:
        raise ValueError("empty training dataset")
    feature_keys = [k for k in rows[0] if k != label_column]
    X = np.asarray([[row[k] for k in feature_keys] for row in rows])
    y = np.asarray([row[label_column] for row in rows])
    return X, y, feature_keys


class SklearnTrainer(BaseTrainer):
    """Fits `estimator` on datasets["train"] (a ray_tpu Dataset, or a
    dict of numpy arrays {"x": ..., "y": ...}); optional "valid" dataset
    adds a validation score.  The fit runs in a remote worker so driver
    memory/GIL stay free (reference runs it in a trainable actor)."""

    def __init__(self, *, estimator, datasets: Dict[str, Any],
                 label_column: Optional[str] = None, **kwargs):
        super().__init__(**kwargs)
        if "train" not in datasets:
            raise ValueError('datasets must contain a "train" entry')
        for name, d in datasets.items():
            if not isinstance(d, dict) and label_column is None:
                # Fail at construction, not with a KeyError(None) deep in
                # the remote fit worker.
                raise ValueError(
                    f'dataset "{name}" is a Dataset of rows — pass '
                    "label_column= to name the target column "
                    '(numpy-dict datasets {"x": ..., "y": ...} do not '
                    "need it)")
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column

    def _run(self, checkpoint: Optional[Checkpoint]) -> Result:
        import ray_tpu

        estimator, datasets, label = (self.estimator, self.datasets,
                                      self.label_column)

        @ray_tpu.remote(max_retries=0)
        def fit_remote():
            import pickle

            def to_xy(d):
                if isinstance(d, dict):
                    return np.asarray(d["x"]), np.asarray(d["y"]), None
                return _dataset_to_xy(d, label)

            X, y, feats = to_xy(datasets["train"])
            estimator.fit(X, y)
            metrics = {"train_score": float(estimator.score(X, y)),
                       "n_samples": int(len(y))}
            if "valid" in datasets:
                Xv, yv, _ = to_xy(datasets["valid"])
                metrics["valid_score"] = float(estimator.score(Xv, yv))
            return metrics, pickle.dumps(estimator), feats

        metrics, blob, feats = ray_tpu.get(fit_remote.remote())
        ckpt = Checkpoint.from_dict({_MODEL_KEY: blob,
                                     "feature_keys": feats})
        self._latest_checkpoint = ckpt
        return Result(metrics=metrics, checkpoint=ckpt,
                      metrics_history=[metrics])


class SklearnPredictor(Predictor):
    """Batch inference over a fitted estimator (reference:
    sklearn_predictor.py); plugs into BatchPredictor."""

    def __init__(self, model, feature_keys=None):
        self.model = model
        self.feature_keys = feature_keys

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kw):
        import pickle

        d = checkpoint.to_dict()
        return cls(pickle.loads(d[_MODEL_KEY]),
                   feature_keys=d.get("feature_keys"), **kw)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        if "x" in batch:
            X = np.asarray(batch["x"])
        else:
            keys = self.feature_keys or sorted(batch)
            X = np.stack([np.asarray(batch[k]) for k in keys], axis=1)
        return {"predictions": np.asarray(self.model.predict(X))}
