"""Backend interface (reference: python/ray/train/backend.py Backend/
BackendConfig; the torch/NCCL impl it replaces: train/torch/config.py:69)."""
from __future__ import annotations

from typing import Any, Dict, List


class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks run by the BackendExecutor around worker-group lifetime."""

    def on_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_training_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_shutdown(self, worker_group, backend_config: BackendConfig):
        pass


class TestConfig(BackendConfig):
    """No-op backend for executor tests (reference:
    python/ray/train/tests/test_backend.py:45)."""

    def backend_cls(self):
        return Backend
