"""Backend interface (reference: python/ray/train/backend.py Backend/
BackendConfig; the torch/NCCL impl it replaces: train/torch/config.py:69)."""
from __future__ import annotations

from typing import Any, Dict, List


class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks run by the BackendExecutor around worker-group lifetime."""

    def on_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_training_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_training_failure(self, worker_group, backend_config: BackendConfig,
                            error: BaseException):
        """Called when the executor detects a gang-poisoning failure (a
        rank's process died, or the group missed its deadline) BEFORE the
        worker group is torn down for an elastic restart.  Backends log /
        record state here; the group itself is unusable — surviving ranks
        may be stuck in a dead collective (reference:
        BackendExecutor._increment_failures + backend failure handling)."""
        pass

    def on_shutdown(self, worker_group, backend_config: BackendConfig):
        pass


class TestConfig(BackendConfig):
    """No-op backend for executor tests (reference:
    python/ray/train/tests/test_backend.py:45)."""

    def backend_cls(self):
        return Backend
