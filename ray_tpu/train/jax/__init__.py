"""JAX trainer (the framework's TorchTrainer equivalent — reference:
python/ray/train/torch/torch_trainer.py)."""
from ray_tpu.train.jax.config import JaxConfig  # noqa: F401
from ray_tpu.train.jax.train_loop_utils import (  # noqa: F401
    AsyncMetrics,
    compile_donated_step,
    compile_zero_step,
    get_mesh,
    prepare_batch,
    prepare_device_iterator,
    prepare_train_state,
)
from ray_tpu.train.base_trainer import DataParallelTrainer


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer with the jax.distributed backend.

    The per-worker loop runs a pjit/shard_map program over the group's
    mesh; gradients ride XLA collectives, not the object store."""

    def __init__(self, train_loop_per_worker, *, jax_config=None, **kw):
        kw.setdefault("backend_config", jax_config or JaxConfig())
        super().__init__(train_loop_per_worker, **kw)
