"""In-loop helpers (reference: python/ray/train/torch/train_loop_utils.py —
prepare_model DDP wrap, prepare_data_loader).  The TPU equivalents don't
wrap modules; they build the mesh and place arrays.
"""
from __future__ import annotations

from typing import Any, Optional

from ray_tpu.parallel.mesh import MeshSpec, make_mesh
from ray_tpu.parallel.sharding import ShardingRules, batch_sharding, shard_params


def get_mesh(spec: Optional[MeshSpec] = None):
    """Mesh over all devices visible to this training group.

    After jax.distributed.initialize (multi-host), jax.devices() spans the
    whole group, so the same call yields the global mesh on every worker."""
    return make_mesh(spec or MeshSpec({"data": -1}))


def prepare_train_state(params: Any, mesh, annotations=None,
                        rules: Optional[ShardingRules] = None):
    """Place params on the mesh (replicated or by logical-axis annotation) —
    the moral equivalent of prepare_model's DDP wrap."""
    return shard_params(params, mesh, rules, annotations)


def prepare_batch(batch: Any, mesh):
    """Shard a host batch's leading dim over the data axes."""
    import jax

    def place(x):
        return jax.device_put(x, batch_sharding(mesh, getattr(x, "ndim", 1)))

    return jax.tree_util.tree_map(place, batch)


def compile_donated_step(step_fn, carry_argnums=(0,), batch_argnums=(),
                         donate_batch: bool = False, **jit_kwargs):
    """jit a training step with the carry (params/opt state) — and
    optionally the batch buffers — donated, so XLA updates weights
    in-place instead of allocating a second copy per step (the hot-path
    half of the zero-sync pipeline; see docs/PERFORMANCE.md).

    ``step_fn(carry..., batch...) -> (carry..., metrics)``: the caller
    must not reuse donated arguments after the call (donation invalidates
    their buffers) — keep ``donate_batch=False`` when the same host batch
    is fed to several steps (e.g. synthetic-data benches)."""
    import jax

    donate = tuple(carry_argnums)
    if donate_batch:
        donate = donate + tuple(batch_argnums)
    return jax.jit(step_fn, donate_argnums=donate, **jit_kwargs)


class AsyncMetrics:
    """Every-N async metrics fetch for step loops.

    ``push(step, metrics)`` keeps the (lazy, device-resident) metrics of
    the latest step and only converts them to host floats every
    ``interval`` steps — so the loop never blocks on a per-step
    device_get round trip (~0.1s on tunneled backends).  ``last`` holds
    the most recent host copy; ``flush()`` forces a final fetch (and is
    the loop-end barrier the bench pattern needs)."""

    def __init__(self, interval: int = 10):
        self.interval = max(1, int(interval))
        self._pending = None
        self._pending_step = None
        self.last: Optional[dict] = None
        self.last_step: Optional[int] = None

    def push(self, step: int, metrics: Any) -> Optional[dict]:
        self._pending = metrics
        self._pending_step = step
        if step % self.interval == 0:
            return self.flush()
        return None

    def flush(self) -> Optional[dict]:
        if self._pending is None:
            return self.last
        import jax

        host = jax.device_get(self._pending)
        self.last = {k: (float(v) if hasattr(v, "__float__") else v)
                     for k, v in host.items()} \
            if isinstance(host, dict) else host
        self.last_step = self._pending_step
        self._pending = None
        return self.last


def compile_zero_step(grad_fn, tx, params, mesh=None, *,
                      zero_sharding: str = "opt+grads",
                      quantized_collectives: str = "off",
                      should_shard=None, donate: bool = True):
    """Build a ZeRO data-parallel train step for the Train JAX loop
    (arxiv 2004.13336 + EQuARX int8 collectives; see
    ray_tpu.parallel.zero and docs/PERFORMANCE.md).

    ``grad_fn(params, batch) -> (loss, grads)`` on a LOCAL batch shard
    (e.g. ``jax.value_and_grad`` of the model loss).  Returns
    ``(step, opt_state, info)`` where ``step(params, opt_state, batch) ->
    (params, opt_state, loss)`` is one jitted shard_map program over the
    mesh's ``data`` axis: batch sharded, params replicated, optimizer
    state sharded 1/N per replica, gradients reduce-scattered (int8 when
    ``quantized_collectives="int8"``), fresh params all-gathered, loss
    pmean'd.  ``opt_state`` is the globally-sharded initial state
    (already placed); ``info`` is the memory/wire envelope
    (``zero_opt_bytes_per_replica``, ``grad_comm_bytes``, ...).

    ``tx`` must be elementwise (adam/adamw/sgd/...); for gradient-norm
    clipping chain ``zero.zero_clip_by_global_norm`` instead of
    ``optax.clip_by_global_norm`` — the shard-local norm would otherwise
    be wrong.  The carry is donated by default (in-place weight update,
    same contract as ``compile_donated_step``)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel import zero as zero_mod
    from ray_tpu.rllib.utils.mesh import _shard_map

    if mesh is None:
        mesh = get_mesh()
    axis = zero_mod.DATA_AXIS
    world = dict(mesh.shape).get(axis, 1)
    zu = zero_mod.build_zero_update(
        jax.eval_shape(lambda: params), tx, world,
        zero_sharding=zero_sharding, quantized=quantized_collectives,
        axis_name=axis, should_shard=should_shard)
    info = zero_mod.export_zero_metrics(
        zu.sharder, tx, zero_sharding=zero_sharding,
        quantized=quantized_collectives)

    def body(params, opt_block, batch):
        loss, grads = grad_fn(params, batch)
        loss = jax.lax.pmean(loss, axis) if world > 1 else loss
        params, opt_block = zu.update(grads, opt_block, params)
        return params, opt_block, loss

    mapped = _shard_map(body, mesh=mesh,
                        in_specs=(P(), zu.opt_specs, P(axis)),
                        out_specs=(P(), zu.opt_specs, P()))
    step = jax.jit(mapped, donate_argnums=(0, 1) if donate else ())
    opt_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), zu.opt_specs,
        is_leaf=lambda s: isinstance(s, P))
    opt_state = jax.jit(zu.init_opt, out_shardings=opt_sh)(params)
    return step, opt_state, info


def prepare_device_iterator(host_batches, mesh=None, sharding=None,
                            prefetch: int = 2):
    """Wrap any host-batch iterable in the background device prefetcher,
    sharded over the mesh's data axes when ``mesh`` is given — the Train
    JAX loop's ingest hot path (same machinery as
    Dataset.iter_device_batches; see ray_tpu.data.prefetch)."""
    from ray_tpu.data.prefetch import DevicePrefetcher

    place_fn = None
    if mesh is not None and sharding is None:
        place_fn = lambda b: prepare_batch(b, mesh)  # noqa: E731
    return DevicePrefetcher(host_batches, sharding=sharding,
                            prefetch=prefetch, place_fn=place_fn)
