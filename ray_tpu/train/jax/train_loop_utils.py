"""In-loop helpers (reference: python/ray/train/torch/train_loop_utils.py —
prepare_model DDP wrap, prepare_data_loader).  The TPU equivalents don't
wrap modules; they build the mesh and place arrays.
"""
from __future__ import annotations

from typing import Any, Optional

from ray_tpu.parallel.mesh import MeshSpec, make_mesh
from ray_tpu.parallel.sharding import ShardingRules, batch_sharding, shard_params


def get_mesh(spec: Optional[MeshSpec] = None):
    """Mesh over all devices visible to this training group.

    After jax.distributed.initialize (multi-host), jax.devices() spans the
    whole group, so the same call yields the global mesh on every worker."""
    return make_mesh(spec or MeshSpec({"data": -1}))


def prepare_train_state(params: Any, mesh, annotations=None,
                        rules: Optional[ShardingRules] = None):
    """Place params on the mesh (replicated or by logical-axis annotation) —
    the moral equivalent of prepare_model's DDP wrap."""
    return shard_params(params, mesh, rules, annotations)


def prepare_batch(batch: Any, mesh):
    """Shard a host batch's leading dim over the data axes."""
    import jax

    def place(x):
        return jax.device_put(x, batch_sharding(mesh, getattr(x, "ndim", 1)))

    return jax.tree_util.tree_map(place, batch)
