"""JaxConfig backend: the TPU replacement for _TorchBackend/NCCL setup
(reference: python/ray/train/torch/config.py:69-132 — MASTER_ADDR from
worker 0, dist.init_process_group(nccl) on every worker).

TPU equivalent: the gang rendezvous is delegated to the MeshGroup primitive
(ray_tpu/parallel/mesh_group.py) — worker 0's address becomes the
jax.distributed coordinator, each worker process joins, and from then on
`jax.devices()` spans the whole group.  Gradient traffic is in-graph XLA
collectives over ICI/DCN; no process-group library exists.  Single-worker
groups (one host, N chips) skip rendezvous entirely: pjit over local
devices IS the data-parallel path.
"""
from __future__ import annotations

from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig


class JaxConfig(BackendConfig):
    def __init__(self, platform: Optional[str] = None,
                 local_device_count: Optional[int] = None):
        # platform override for tests ("cpu" meshes); None = autodetect TPU.
        # local_device_count: virtual devices per worker process (the JAX
        # fake-accelerator mode used by multi-process CPU tests).
        self.platform = platform
        self.local_device_count = local_device_count

    def backend_cls(self):
        return _JaxBackend


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig):
        from ray_tpu.parallel.mesh_group import rendezvous

        rendezvous(worker_group.workers, backend_config.platform,
                   backend_config.local_device_count)

    def on_training_failure(self, worker_group, backend_config: JaxConfig,
                            error: BaseException):
        # A dead rank invalidates the whole jax.distributed world: record
        # it so operators can alert on gang churn.  The executor tears the
        # group down right after this; fresh processes re-rendezvous on
        # the next elastic attempt (a stale jax backend cannot rejoin).
        import logging

        from ray_tpu.util.metrics import Counter

        logging.getLogger(__name__).warning(
            "jax.distributed gang failed (%s); the worker group will be "
            "rebuilt and training resumed from the latest checkpoint",
            error)
        try:
            Counter("train_gang_failures_total",
                    "jax.distributed gangs lost to rank death").inc()
        except Exception:
            pass
