"""JaxConfig backend: the TPU replacement for _TorchBackend/NCCL setup
(reference: python/ray/train/torch/config.py:69-132 — MASTER_ADDR from
worker 0, dist.init_process_group(nccl) on every worker).

TPU equivalent: worker 0's address is the jax.distributed coordinator; each
worker process calls `jax.distributed.initialize(coordinator, world_size,
rank)` and from then on `jax.devices()` spans the whole group — gradient
traffic is in-graph XLA collectives over ICI/DCN, no process-group library.
Single-worker groups (one host, N chips) skip rendezvous entirely: pjit over
local devices IS the data-parallel path.
"""
from __future__ import annotations

import os

from ray_tpu.train.backend import Backend, BackendConfig


class JaxConfig(BackendConfig):
    def __init__(self, platform: str | None = None):
        # platform override for tests ("cpu" meshes); None = autodetect TPU.
        self.platform = platform

    def backend_cls(self):
        return _JaxBackend


def _init_jax_distributed(platform):
    """Runs inside each training worker before the user loop."""
    import os

    if platform:
        os.environ.setdefault("JAX_PLATFORMS", platform)
    world = int(os.environ.get("RTPU_WORLD_SIZE", "1"))
    if world > 1:
        import jax

        jax.distributed.initialize(
            coordinator_address=os.environ["RTPU_COORDINATOR"],
            num_processes=world,
            process_id=int(os.environ["RTPU_RANK"]),
        )
    return True


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig):
        worker_group.execute(_init_jax_distributed, backend_config.platform)
