"""BackendExecutor: placement group + worker group + rendezvous + training
loop results (reference: python/ray/train/_internal/backend_executor.py:43 —
PG creation :138, rank assignment :245, start_training :315; restart :571).

Gang fault tolerance: every fan-out to the worker gang resolves through
``mesh_group.gang_get`` (eager rank-death detection — see the fault
tolerance section of ray_tpu/parallel/mesh_group.py), and any
gang-poisoning failure (``MeshGroupError``, actor/worker death, deadline)
is converted into ``TrainingWorkerError`` so ``BaseTrainer.fit`` can tear
the executor down and elastically restart from the latest checkpoint.
"""
from __future__ import annotations

import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.parallel.mesh_group import gang_get, is_transport_abort
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.util.placement_group import (
    placement_group as _create_pg,
    remove_placement_group as _remove_pg,
)


class TrainingWorkerError(Exception):
    """``transport_abort`` marks the gloo TCP race (see
    ``mesh_group.is_transport_abort``): the gang needs a rebuild but the
    failure is environmental, so ``BaseTrainer.fit`` charges it against a
    separate transport budget instead of ``FailureConfig.max_failures``."""

    def __init__(self, cause, tb: str, transport_abort: bool = False):
        self.cause = cause
        self.tb = tb
        self.transport_abort = transport_abort
        super().__init__(f"training worker failed:\n{tb}")


# Failures that mean the gang (not the user code) is broken and a fresh
# worker group + rendezvous can recover.
_GANG_FAILURES = (exc.MeshGroupError, exc.ActorDiedError,
                  exc.ActorUnavailableError, exc.WorkerCrashedError,
                  exc.ObjectLostError)


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig, generation: int = 0,
                 storage_path: Optional[str] = None):
        self.backend_config = backend_config
        self.backend: Backend = backend_config.backend_cls()()
        self.scaling = scaling_config
        # Actual gang size; start() resolves it inside the elastic range.
        self.num_workers = scaling_config.max_workers
        self.worker_group: Optional[WorkerGroup] = None
        self.pg = None
        # Elastic-restart incarnation index (0 on the first attempt);
        # exported to workers so chaos schedules can target one gang.
        self.generation = generation
        # Checkpoint store root, exported to every worker as
        # RTPU_CHECKPOINT_ROOT: rank loops save per-rank shards directly
        # into it (ray_tpu.checkpoint.ShardWriter) and elastic resume
        # discovers the latest committed manifest there.
        self.storage_path = storage_path

    def _gang_failure(self, e: BaseException) -> TrainingWorkerError:
        """Wrap a gang-poisoning failure so the trainer's elastic-restart
        loop (which catches TrainingWorkerError) handles dead ranks the
        same way it handles in-band worker errors."""
        try:
            self.backend.on_training_failure(self.worker_group,
                                             self.backend_config, e)
        except Exception:
            pass
        return TrainingWorkerError(e, traceback.format_exc(),
                                   transport_abort=is_transport_abort(e))

    def start(self):
        """Reserve placement + spawn the gang.  With an elastic
        ``num_workers=(min, max)`` range, probe sizes max→min and take
        the largest the cluster can place NOW (never below min —
        min's placement failure propagates)."""
        res = self.scaling.worker_resources()
        lo, hi = self.scaling.worker_range()
        self.num_workers = lo
        for n in range(hi, lo - 1, -1):
            if n == 1:
                self.num_workers = 1
                break
            bundles = [dict(res) for _ in range(n)]
            pg = _create_pg(bundles,
                            strategy=self.scaling.placement_strategy)
            try:
                # The floor size gets the full grace period; larger probe
                # sizes fail fast so a tight cluster degrades quickly.
                pg.ready(timeout=60 if n == lo else 10)
            except Exception:
                try:
                    _remove_pg(pg)
                except Exception:
                    pass
                if n == lo:
                    raise
                continue
            self.pg = pg
            self.num_workers = n
            break
        self.worker_group = WorkerGroup(self.num_workers, res,
                                        self.pg, generation=self.generation)
        if self.storage_path:
            try:
                gang_get([w.setup_env.remote(
                    {"RTPU_CHECKPOINT_ROOT": self.storage_path})
                    for w in self.worker_group.workers], timeout=30.0)
            except _GANG_FAILURES as e:
                raise self._gang_failure(e) from e
        # Gang rendezvous (jax.distributed coordinator on worker 0) is the
        # backend's job, shared with MeshGroup: see
        # ray_tpu/parallel/mesh_group.py:rendezvous.  A rank dying inside
        # the rendezvous is a recoverable gang failure, not a user error.
        try:
            self.backend.on_start(self.worker_group, self.backend_config)
        except _GANG_FAILURES as e:
            raise self._gang_failure(e) from e

    def start_training(self, train_fn: Callable, config: dict,
                       checkpoint: Optional[Checkpoint] = None,
                       dataset_shards: Optional[List[dict]] = None):
        self.backend.on_training_start(self.worker_group, self.backend_config)
        try:
            gang_get([
                w.start_training.remote(
                    train_fn, config, checkpoint,
                    dataset_shards[i] if dataset_shards else None)
                for i, w in enumerate(self.worker_group.workers)
            ])
        except _GANG_FAILURES as e:
            raise self._gang_failure(e) from e

    def get_next_results(self, timeout: float = 600.0) -> Optional[List[tuple]]:
        """Blocks for one result per worker. Returns None when all done.
        Raises TrainingWorkerError on any worker error — in-band ("error"
        results) or out-of-band (a rank's process died: gang_get detects
        it eagerly instead of blocking on the surviving, possibly
        collective-stuck, peers)."""
        try:
            # Slack past the workers' own queue timeout: a healthy worker
            # answers ("timeout", ...) in-band at `timeout`; the gang_get
            # deadline only fires for ranks that can't answer at all.
            results = gang_get([w.next_result.remote(timeout)
                                for w in self.worker_group.workers],
                               timeout=timeout + 30.0)
        except _GANG_FAILURES as e:
            raise self._gang_failure(e) from e
        kinds = {r[0] for r in results}
        if "error" in kinds:
            for r in results:
                if r[0] == "error":
                    raise TrainingWorkerError(
                        r[1], r[2],
                        transport_abort=is_transport_abort(r[1]))
        if kinds == {"done"}:
            return None
        if "timeout" in kinds:
            raise TimeoutError("training workers produced no result in time")
        return results

    def ping_workers(self, deadline: float = 10.0) -> List[int]:
        """Health-probe the gang (MeshGroup.health_check shape); raises
        MeshGroupError naming dead/unresponsive ranks."""
        return gang_get([w.ping.remote()
                         for w in self.worker_group.workers],
                        timeout=deadline)

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
        if self.pg is not None:
            try:
                _remove_pg(self.pg)
            except Exception:
                pass
            self.pg = None
