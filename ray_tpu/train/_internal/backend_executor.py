"""BackendExecutor: placement group + worker group + rendezvous + training
loop results (reference: python/ray/train/_internal/backend_executor.py:43 —
PG creation :138, rank assignment :245, start_training :315; restart :571).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.util.placement_group import (
    placement_group as _create_pg,
    remove_placement_group as _remove_pg,
)


class TrainingWorkerError(Exception):
    def __init__(self, cause, tb: str):
        self.cause = cause
        self.tb = tb
        super().__init__(f"training worker failed:\n{tb}")


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig):
        self.backend_config = backend_config
        self.backend: Backend = backend_config.backend_cls()()
        self.scaling = scaling_config
        self.worker_group: Optional[WorkerGroup] = None
        self.pg = None

    def start(self):
        res = self.scaling.worker_resources()
        if self.scaling.num_workers > 1:
            bundles = [dict(res) for _ in range(self.scaling.num_workers)]
            self.pg = _create_pg(
                bundles, strategy=self.scaling.placement_strategy)
            self.pg.ready(timeout=60)
        self.worker_group = WorkerGroup(self.scaling.num_workers, res, self.pg)
        # Gang rendezvous (jax.distributed coordinator on worker 0) is the
        # backend's job, shared with MeshGroup: see
        # ray_tpu/parallel/mesh_group.py:rendezvous.
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(self, train_fn: Callable, config: dict,
                       checkpoint: Optional[Checkpoint] = None,
                       dataset_shards: Optional[List[dict]] = None):
        self.backend.on_training_start(self.worker_group, self.backend_config)
        ray_tpu.get([
            w.start_training.remote(
                train_fn, config, checkpoint,
                dataset_shards[i] if dataset_shards else None)
            for i, w in enumerate(self.worker_group.workers)
        ])

    def get_next_results(self, timeout: float = 600.0) -> Optional[List[tuple]]:
        """Blocks for one result per worker. Returns None when all done.
        Raises TrainingWorkerError on any worker error (reference surfaces
        the first failure the same way)."""
        results = ray_tpu.get([w.next_result.remote(timeout)
                               for w in self.worker_group.workers])
        kinds = {r[0] for r in results}
        if "error" in kinds:
            for r in results:
                if r[0] == "error":
                    raise TrainingWorkerError(r[1], r[2])
        if kinds == {"done"}:
            return None
        if "timeout" in kinds:
            raise TimeoutError("training workers produced no result in time")
        return results

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
        if self.pg is not None:
            try:
                _remove_pg(self.pg)
            except Exception:
                pass
            self.pg = None

