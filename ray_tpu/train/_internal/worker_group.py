"""WorkerGroup: the gang of training-worker actors.

Reference: python/ray/train/_internal/worker_group.py:92 (plain actors with
execute/execute_async).  Here each worker is a TrainWorker actor
(max_concurrency=2 so result polling overlaps the training loop), spawned
under a placement group for gang scheduling — on TPU this is the unit that
*hosts a mesh*: one worker per TPU host.

The training loop runs on a ``flow.Stage(sink=True)`` (the dataflow
substrate's terminal stage: one background worker consuming a single-item
source by side effect) rather than a hand-rolled ``threading.Thread``;
results still flow to the driver through the ``queue.Queue`` result
mailbox — a mailbox, not a pipeline, so it stays.
"""
from __future__ import annotations

import queue
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air import session as air_session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.parallel import flow


@ray_tpu.remote
class TrainWorker:
    """Actor hosting one training process (one TPU host's worth of chips)."""

    def __init__(self, rank: int, world_size: int, generation: int = 0):
        import os

        from ray_tpu._private import chaos

        self.rank = rank
        self.world_size = world_size
        self.generation = generation
        self._results: "queue.Queue" = queue.Queue()
        self._stage: Optional[flow.Stage] = None
        self._env: Dict[str, str] = {}
        # Gang generation: lets the chaos kill schedule target exactly one
        # incarnation, so an elastically-restarted gang survives.
        os.environ[chaos.GENERATION_ENV] = str(generation)

    def ping(self) -> int:
        """Liveness probe; answers on the spare concurrency slot even
        while the training loop runs."""
        return self.rank

    def setup_env(self, env: Dict[str, str]):
        import os

        self._env.update(env)
        os.environ.update(env)
        return True

    def node_info(self) -> dict:
        import os
        import socket

        return {"rank": self.rank, "pid": os.getpid(),
                "host": socket.gethostname()}

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker (reference
        WorkerGroup.execute)."""
        return fn(*args, **kwargs)

    def start_training(self, train_fn: Callable, config: dict,
                      checkpoint: Optional[Checkpoint],
                      dataset_shards: Optional[dict] = None) -> bool:
        """Launch the user loop on a sink stage; results flow via
        next_result."""

        def report_fn(metrics, ckpt):
            from ray_tpu._private import chaos

            # Chaos kill site: a schedule entry "train_report:<rank>:<nth>"
            # SIGKILLs this host at its nth report — the deterministic
            # stand-in for a TPU host preemption mid-training.
            chaos.maybe_die("train_report", self.rank)
            self._results.put(("report", metrics, ckpt))

        def run(_item):
            import inspect
            import os

            from ray_tpu.air import session as air_session

            air_session.init_session(
                report_fn=report_fn, world_rank=self.rank,
                world_size=self.world_size, checkpoint=checkpoint,
                dataset_shards=dataset_shards,
                storage_path=os.environ.get("RTPU_CHECKPOINT_ROOT"))
            try:
                wants_arg = True
                try:
                    wants_arg = len(inspect.signature(train_fn).parameters) >= 1
                except (TypeError, ValueError):
                    pass
                out = train_fn(config) if wants_arg else train_fn()
                self._results.put(("done", out, None))
            except BaseException as e:  # noqa: BLE001 — shipped to driver
                import traceback

                self._results.put(("error", e, traceback.format_exc()))
            finally:
                air_session.shutdown_session()

        if self._stage is not None:
            self._stage.close()
        # One-item source, sink=True: the stage's single worker runs the
        # whole training loop as the side effect of consuming that item.
        self._stage = flow.Stage(iter([None]), run, sink=True, workers=1,
                                 depth=1, name="train-loop",
                                 export_metrics=False)
        return True

    def next_result(self, timeout: float = 3600.0):
        try:
            return self._results.get(timeout=timeout)
        except queue.Empty:
            return ("timeout", None, None)


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 placement_group=None, generation: int = 0):
        opts: Dict[str, Any] = {"max_concurrency": 2}
        cpu = resources_per_worker.get("CPU", 1.0)
        opts["num_cpus"] = cpu
        if resources_per_worker.get("TPU"):
            opts["num_tpus"] = resources_per_worker["TPU"]
        extra = {k: v for k, v in resources_per_worker.items()
                 if k not in ("CPU", "TPU")}
        if extra:
            opts["resources"] = extra
        if placement_group is not None:
            from ray_tpu.util import PlacementGroupSchedulingStrategy

            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group)
        self.workers = [
            TrainWorker.options(**opts).remote(rank, num_workers, generation)
            for rank in range(num_workers)
        ]
        self.num_workers = num_workers
        self.generation = generation

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(self.workers[rank].execute.remote(fn, *args, **kwargs))

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
