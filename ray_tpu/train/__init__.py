"""Ray-Train-equivalent distributed training (reference: python/ray/train/)."""
from ray_tpu.train.backend import Backend, BackendConfig, TestConfig  # noqa: F401
from ray_tpu.train.base_trainer import (  # noqa: F401
    BaseTrainer,
    DataParallelTrainer,
)
from ray_tpu.train.gbdt import LightGBMTrainer, XGBoostTrainer  # noqa: F401
from ray_tpu.train.jax import JaxConfig, JaxTrainer  # noqa: F401
from ray_tpu.train.sklearn import SklearnPredictor, SklearnTrainer  # noqa: F401
from ray_tpu.train._internal.backend_executor import (  # noqa: F401
    BackendExecutor,
    TrainingWorkerError,
)
from ray_tpu.train._internal.worker_group import WorkerGroup  # noqa: F401
from ray_tpu.train.predictor import (  # noqa: F401
    BatchPredictor,
    JaxPredictor,
    Predictor,
)
