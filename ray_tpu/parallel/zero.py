"""ZeRO-style cross-replica sharding of the weight update + optimizer state.

Reference: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arxiv 2004.13336) — in plain data parallelism
every replica holds the full optimizer state and applies the identical
update N times; sharding the update gives each replica 1/N of the
parameters to own: gradients arrive by **reduce-scatter** (each replica
only materializes the mean gradient of its shard), the optax update runs
on the shard with the replica's 1/N optimizer-state slice, and the fresh
parameters are **all-gathered** back so the forward/backward still sees
fully replicated weights.  Optimizer memory drops ~N× (Adam state alone
is 2× parameters, 8× at fp32 state over bf16 params) and the gradient
wire halves vs all-reduce — or drops ~8× combined with the int8
collectives in ``ray_tpu.ops.collectives``.

Layout: the sharded leaves are flattened (tree-leaf order) into ONE flat
vector of ``total`` elements, zero-padded to ``world * chunk`` with
``chunk = ceil(total / world)`` — equal chunks are what the collectives
need; the padding tail lives on the last rank(s) and is remainder slack.
Leaves a ``should_shard`` predicate rejects (and all scalars) stay
replicated with replicated optimizer state and a plain ``pmean`` gradient
— the mixed replicated/sharded layout mirroring
``checkpoint.tree.axis0_shard_index``'s ``should_shard``.

The optimizer update runs on a combined pytree ``{"shard": [chunk],
"repl": (...)}`` so one ``tx`` covers both partitions; any optax chain of
elementwise transforms (adam/adamw/sgd/scale) is shard-equivalent to the
replicated update by construction, and ``zero_clip_by_global_norm``
replaces ``optax.clip_by_global_norm`` (whose norm is global, not
elementwise) with a psum-reconstructed exact global norm.

Checkpointing: the optimizer state is *natively sharded*, so saves go
through the PR 4 distributed checkpointer as per-rank shard files whose
``[start, stop]`` indices cover the unpadded ``(total,)`` global vector —
``save_opt_state`` / ``restore_opt_state`` round-trip an N-way state onto
an M-way gang (the elastic-restart contract; see docs/CHECKPOINTING.md).
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.ops import collectives

DATA_AXIS = "data"  # must match ray_tpu.rllib.utils.mesh.DATA_AXIS


def _keystr(kp) -> str:
    try:
        return jax.tree_util.keystr(kp)
    except Exception:  # pragma: no cover — ancient jax
        return "/".join(str(k) for k in kp)


def _is_shard_path(kp) -> bool:
    """True for opt-state leaves living under the combined tree's
    ``"shard"`` branch (the 1/N flat-vector partition)."""
    for k in kp:
        if getattr(k, "key", None) == "shard":
            return True
    return False


class ZeroSharder:
    """Partition bookkeeping for a ZeRO update over ``world`` replicas.

    Built host-side from a parameter template (arrays or
    ``jax.ShapeDtypeStruct``s); every method that touches traced values is
    safe inside jit/shard_map.  ``should_shard(path)`` (path =
    ``jax.tree_util.keystr`` of the leaf) keeps rejected leaves — and all
    scalars — replicated."""

    def __init__(self, params_template: Any, world: int,
                 should_shard: Optional[Callable[[str], bool]] = None):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.world = int(world)
        leaves_kp, self.treedef = jax.tree_util.tree_flatten_with_path(
            params_template)
        self._sharded_mask: list = []
        sizes, dtypes = [], []
        for kp, leaf in leaves_kp:
            nd = getattr(leaf, "ndim", 0)
            shard = nd >= 1 and (should_shard is None
                                 or should_shard(_keystr(kp)))
            self._sharded_mask.append(bool(shard))
            if shard:
                sizes.append(int(np.prod(leaf.shape)))
                dtypes.append(jnp.dtype(leaf.dtype))
        self._shapes = [tuple(leaf.shape) for _, leaf in leaves_kp]
        self._dtypes = [jnp.dtype(getattr(leaf, "dtype", jnp.float32))
                        for _, leaf in leaves_kp]
        if not sizes:
            raise ValueError("ZeroSharder: no sharded leaves (all scalars "
                             "or rejected by should_shard)")
        self.dtype = jnp.result_type(*dtypes)
        self.total = int(sum(sizes))
        self.chunk = -(-self.total // self.world)
        self.padded = self.chunk * self.world
        self._offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(int)

    # ---- flat-vector plumbing (trace-safe) ----
    def split(self, tree: Any) -> Tuple[jax.Array, Tuple]:
        """(flat [padded] vector of the sharded leaves, tuple of the
        replicated leaves) — inverse of ``merge``."""
        leaves = self.treedef.flatten_up_to(tree)
        parts, repl = [], []
        for leaf, shard in zip(leaves, self._sharded_mask):
            if shard:
                parts.append(jnp.ravel(leaf).astype(self.dtype))
            else:
                repl.append(leaf)
        flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        if self.padded > self.total:
            flat = jnp.concatenate(
                [flat, jnp.zeros((self.padded - self.total,), self.dtype)])
        return flat, tuple(repl)

    def merge(self, flat: jax.Array, repl: Sequence) -> Any:
        """Rebuild the full pytree from a [padded] flat vector + the
        replicated leaves (cast back to each leaf's dtype/shape)."""
        repl = list(repl)
        leaves, si = [], 0
        for i, shard in enumerate(self._sharded_mask):
            if shard:
                start = int(self._offsets[si])
                stop = int(self._offsets[si + 1])
                leaves.append(flat[start:stop].reshape(self._shapes[i])
                              .astype(self._dtypes[i]))
                si += 1
            else:
                leaves.append(repl.pop(0))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def rows(self, flat: jax.Array) -> jax.Array:
        return flat.reshape(self.world, self.chunk)

    # ---- sharded optimizer state ----
    def init_opt_state(self, tx, params: Any) -> Any:
        """GLOBAL sharded optimizer state: every opt leaf derived from the
        flat-vector partition has shape ``[world, chunk]`` (shard i = rank
        i's slice); everything else (counts, replicated-leaf state) is
        replicated.  Safe under jit with ``out_shardings`` from
        ``opt_specs``."""
        flat, repl = self.split(params)
        rows = self.rows(flat)

        def init_row(row):
            return tx.init({"shard": row, "repl": repl})

        full = jax.vmap(init_row)(rows)
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: x if (_is_shard_path(kp) and x.ndim >= 2)
            else x[0], full)

    def opt_specs(self, tx) -> Any:
        """PartitionSpec pytree for the global opt state (axis-0 sharded
        ``[world, chunk]`` leaves on the data axis, rest replicated)."""
        from jax.sharding import PartitionSpec as P

        tmpl = self._opt_template(tx)
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: P(DATA_AXIS)
            if (_is_shard_path(kp) and x.ndim >= 2) else P(), tmpl)

    def _opt_template(self, tx):
        """ShapeDtypeStruct tree of the GLOBAL opt state."""
        p_tmpl = jax.tree_util.tree_unflatten(
            self.treedef,
            [jax.ShapeDtypeStruct(s, d)
             for s, d in zip(self._shapes, self._dtypes)])
        return jax.eval_shape(lambda p: self.init_opt_state(tx, p), p_tmpl)

    def wrap_opt(self, opt_local: Any) -> Any:
        """Local ``[chunk]`` shard leaves back to the shard_map block view
        ``[1, chunk]`` (the inverse of ``unwrap_opt``)."""
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: x[None]
            if (_is_shard_path(kp) and getattr(x, "ndim", 0) >= 1) else x,
            opt_local)

    def unwrap_opt(self, opt_block: Any) -> Any:
        return jax.tree_util.tree_map_with_path(
            lambda kp, x: x[0]
            if (_is_shard_path(kp) and getattr(x, "ndim", 0) >= 2) else x,
            opt_block)

    # ---- accounting ----
    def opt_bytes_per_replica(self, tx) -> int:
        """Bytes of optimizer state ONE replica holds under this sharder
        (chunk-sized slices of sharded leaves + full replicated leaves)."""
        total = 0
        for kp, leaf in jax.tree_util.tree_flatten_with_path(
                self._opt_template(tx))[0]:
            n = int(np.prod(leaf.shape)) if leaf.ndim else 1
            if _is_shard_path(kp) and leaf.ndim >= 2:
                n = n // self.world  # [world, chunk] → one row
            total += n * jnp.dtype(leaf.dtype).itemsize
        return total

    def replicated_opt_bytes(self, tx) -> int:
        """Bytes of the fully-replicated baseline optimizer state (what
        every replica holds without ZeRO) — the 1/N denominator."""
        p_tmpl = jax.tree_util.tree_unflatten(
            self.treedef,
            [jax.ShapeDtypeStruct(s, d)
             for s, d in zip(self._shapes, self._dtypes)])
        opt = jax.eval_shape(tx.init, p_tmpl)
        return sum(int(np.prod(x.shape) or 1) * jnp.dtype(x.dtype).itemsize
                   for x in jax.tree_util.tree_leaves(opt))

    def comm_accounting(self, zero_sharding: str = "opt+grads",
                        quantized: str = "off",
                        block: int = collectives.DEFAULT_BLOCK) -> dict:
        return collectives.comm_bytes_accounting(
            self.total, self.world, zero_sharding=zero_sharding,
            quantized=quantized, block=block)

    # ---- checkpoint resharding (PR 4 distributed checkpointer) ----
    def valid_range(self, rank: int) -> Tuple[int, int]:
        """Unpadded ``[start, stop)`` of ``rank``'s chunk against the
        global ``(total,)`` vector (the last rank(s) absorb the padding)."""
        start = min(rank * self.chunk, self.total)
        return start, min((rank + 1) * self.chunk, self.total)

    def opt_shard_for_rank(self, opt_global: Any, rank: int) -> Any:
        """Rank ``rank``'s trimmed local opt tree (shard leaves are the
        1-D valid slice, padding dropped) — what that rank persists."""
        start, stop = self.valid_range(rank)

        def pick(kp, x):
            if _is_shard_path(kp) and getattr(x, "ndim", 0) >= 2:
                return x[rank][: stop - start]
            return x

        return jax.tree_util.tree_map_with_path(pick, opt_global)

    def opt_save_index_fn(self, rank: int, local_tree: Any):
        """Save-side ``IndexFn`` for ``ShardWriter``: shard leaves map to
        their ``[start, stop]`` slice of the ``(total,)`` global vector,
        everything else is replicated (rank 0 persists it once)."""
        from ray_tpu.checkpoint.tree import flatten_with_paths

        mask = jax.tree_util.tree_map_with_path(
            lambda kp, x: _is_shard_path(kp)
            and getattr(x, "ndim", 0) >= 1, local_tree)
        sharded_paths = {p for p, v in flatten_with_paths(mask) if v}
        start, stop = self.valid_range(rank)

        def fn(path: str, arr):
            if path not in sharded_paths:
                return None
            return (self.total,), [[start, stop]]

        return fn

    def reshard_opt_state(self, assembled: Any) -> Any:
        """Re-chunk an assembled opt state (shard leaves as full
        ``(total,)`` vectors) onto THIS sharder's world size: pad to
        ``[world, chunk]``; replicated leaves pass through."""

        def redistribute(kp, x):
            if _is_shard_path(kp) and getattr(x, "ndim", 0) == 1 \
                    and int(x.shape[0]) == self.total:
                pad = self.padded - self.total
                if pad:
                    x = jnp.concatenate(
                        [jnp.asarray(x),
                         jnp.zeros((pad,), jnp.asarray(x).dtype)])
                return jnp.asarray(x).reshape(self.world, self.chunk)
            return x

        return jax.tree_util.tree_map_with_path(redistribute, assembled)


# ---- optax pieces ----
def zero_clip_by_global_norm(max_norm: float, axis_name: str = DATA_AXIS):
    """``optax.clip_by_global_norm`` for the combined ``{"shard","repl"}``
    update tree inside a ZeRO shard_map body: the shard partition's
    squared norm is psum'd across the axis (each replica holds 1/N of the
    flat vector; padding contributes 0), replicated leaves count once —
    reconstructing exactly the global norm the replicated path clips by."""
    import optax

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        shard_sq = jax.lax.psum(
            jnp.sum(jnp.square(updates["shard"].astype(jnp.float32))),
            axis_name)
        repl_sq = sum(
            (jnp.sum(jnp.square(x.astype(jnp.float32)))
             for x in jax.tree_util.tree_leaves(updates["repl"])),
            jnp.zeros((), jnp.float32))
        g_norm = jnp.sqrt(shard_sq + repl_sq)
        trigger = g_norm < max_norm
        clip = jax.tree_util.tree_map(
            lambda t: jax.lax.select(
                trigger, t, (t / g_norm.astype(t.dtype)) * max_norm),
            updates)
        return clip, state

    return optax.GradientTransformation(init_fn, update_fn)


def make_update_fn(sharder: ZeroSharder, tx, *,
                   axis_name: str = DATA_AXIS,
                   zero_sharding: str = "opt+grads",
                   quantized: str = "off",
                   block: int = collectives.DEFAULT_BLOCK):
    """The ZeRO gradient-application step, for use INSIDE a shard_map body
    where ``grads``/``params`` are the (replicated) local views and
    ``opt_block`` is the local ``[1, chunk]`` slice of the sharded state.

    ``update(grads, opt_block, params [, rng]) -> (params, opt_block)``:
    reduce-scatter the flat gradient (mean; int8 when ``quantized``),
    apply ``tx`` to this replica's param/opt shard plus the replicated
    remainder, all-gather the fresh param shards.  ``zero_sharding="opt"``
    all-reduces the full gradient first (ZeRO-1 wire; same algebra),
    ``"opt+grads"`` reduce-scatters (ZeRO-2).  ``rng`` enables stochastic
    rounding on the quantized wire."""
    import optax

    if zero_sharding not in ("opt", "opt+grads"):
        raise ValueError(f"zero_sharding must be opt|opt+grads, "
                         f"got {zero_sharding!r}")
    if quantized not in ("off", "int8"):
        raise ValueError(f"quantized must be off|int8, got {quantized!r}")
    world = sharder.world

    def update(grads, opt_block, params, rng=None):
        g_flat, g_repl = sharder.split(grads)
        p_flat, p_repl = sharder.split(params)
        g_repl = tuple(jax.lax.pmean(g, axis_name) for g in g_repl)
        rows = sharder.rows(g_flat)
        if world == 1:
            g_shard = rows[0]
        elif zero_sharding == "opt+grads":
            if quantized == "int8":
                g_shard = collectives.quantized_reduce_scatter_mean(
                    rows, axis_name, block, rng)
            else:
                g_shard = jax.lax.psum_scatter(
                    rows, axis_name, scatter_dimension=0) / world
        else:  # "opt": full all-reduce, then slice this replica's row
            if quantized == "int8":
                g_mean = collectives.quantized_pmean(
                    g_flat, axis_name, world, block, rng)
            else:
                g_mean = jax.lax.pmean(g_flat, axis_name)
            g_shard = sharder.rows(g_mean)[jax.lax.axis_index(axis_name)]
        idx = jax.lax.axis_index(axis_name) if world > 1 else 0
        p_shard = sharder.rows(p_flat)[idx]
        c_grads = {"shard": g_shard.astype(sharder.dtype),
                   "repl": g_repl}
        c_params = {"shard": p_shard, "repl": p_repl}
        updates, opt_out = tx.update(c_grads, sharder.unwrap_opt(opt_block),
                                     c_params)
        new_c = optax.apply_updates(c_params, updates)
        if world > 1:
            new_flat = jax.lax.all_gather(new_c["shard"], axis_name,
                                          tiled=True)
        else:
            new_flat = new_c["shard"]
        return (sharder.merge(new_flat, new_c["repl"]),
                sharder.wrap_opt(opt_out))

    return update


# ---- snapshot/restore placement (MPMD stage snapshots, gang-aware) ----
def replicate_opt_state(opt_state: Any, mesh) -> Any:
    """All-gather a natively-sharded optimizer state into replicated
    arrays on ``mesh`` (one compiled identity with replicated
    out_shardings).  Snapshot path, not the hot path: every process of a
    multi-host mesh ends holding the full state, so any rank's host copy
    can restore any future gang shape."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    repl = NamedSharding(mesh, P())
    shardings = jax.tree_util.tree_map(lambda _: repl, opt_state)
    return jax.jit(lambda o: o, out_shardings=shardings)(opt_state)


def place_opt_state(host_opt: Any, mesh, opt_specs: Any,
                    multihost: bool = False) -> Any:
    """Place a host (replicated-layout) optimizer state onto ``mesh``
    with the ZeRO shardings in ``opt_specs`` — the inverse of
    ``replicate_opt_state`` + ``device_get``.  ``multihost=True`` routes
    through ``jax.make_array_from_callback`` so each process
    materializes only its addressable shards (``device_put`` cannot
    target non-addressable devices)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), opt_specs,
        is_leaf=lambda s: isinstance(s, P))

    def place(x, sh):
        arr = np.asarray(x)
        if not multihost:
            return jax.device_put(jnp.asarray(arr), sh)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx, _a=arr: _a[idx])

    return jax.tree_util.tree_map(place, host_opt, shardings)


# ---- metrics ----
def export_zero_metrics(sharder: ZeroSharder, tx, *, zero_sharding: str,
                        quantized: str) -> dict:
    """Compute the memory/wire envelope and (best-effort) publish the
    ``zero_opt_bytes_per_replica`` / ``grad_comm_bytes`` gauges the
    dashboard exports; returns the numbers either way."""
    acct = sharder.comm_accounting(zero_sharding=zero_sharding,
                                   quantized=quantized)
    out = {
        "zero_opt_bytes_per_replica": sharder.opt_bytes_per_replica(tx),
        "replicated_opt_bytes": sharder.replicated_opt_bytes(tx),
        "grad_comm_bytes": acct["grad_comm_bytes"],
        "param_comm_bytes": acct["param_comm_bytes"],
        "grad_comm_reduction_vs_fp32": acct["reduction_vs_fp32"],
    }
    try:
        from ray_tpu.util.metrics import Gauge

        Gauge("zero_opt_bytes_per_replica",
              "optimizer-state bytes held per replica under ZeRO "
              "sharding").set(float(out["zero_opt_bytes_per_replica"]))
        Gauge("grad_comm_bytes",
              "gradient-reduction bytes moved per replica per update "
              "(analytic ring model)").set(float(out["grad_comm_bytes"]))
    except Exception:
        pass  # no connected runtime (plain jit tests): numbers still return
    return out


# ---- distributed-checkpointer round trip (PR 4 machinery) ----
def save_opt_state(root: str, step: int, sharder: ZeroSharder,
                   opt_global: Any, extra: Optional[dict] = None) -> dict:
    """Persist a natively-sharded optimizer state through the PR 4
    distributed checkpointer: one ``ShardWriter`` per rank writes that
    rank's trimmed shard with exact ``[start, stop]`` indices against the
    unpadded ``(total,)`` flat vector, then the manifest commits.  In a
    real gang each rank runs its own writer; driver-side callers (tests,
    the learner-group hook) iterate ranks in-process."""
    from ray_tpu.checkpoint import manifest as mf
    from ray_tpu.checkpoint.saver import ShardWriter

    host = jax.device_get(opt_global)
    stats = []
    for rank in range(sharder.world):
        local = sharder.opt_shard_for_rank(host, rank)
        writer = ShardWriter(root, rank=rank, world_size=sharder.world)
        stats.append(writer.persist(
            writer.snapshot(local), step,
            index_fn=sharder.opt_save_index_fn(rank, local),
            extra=dict(extra or {}, zero_total=sharder.total)))
    manifest = mf.commit_manifest(root, step, sharder.world,
                                  meta={"zero_total": sharder.total})
    return {"manifest": manifest, "ranks": stats}


def restore_opt_state(root: str, sharder: ZeroSharder, tx,
                      step: Optional[int] = None) -> Any:
    """Restore a sharded optimizer state saved from ANY world size onto
    ``sharder.world`` replicas: assemble the ``(total,)`` globals from
    whichever rank shards cover them, then re-chunk for this gang —
    the N→M elastic-restart path."""
    from ray_tpu.checkpoint.restore import restore_tree

    target = sharder._opt_template(tx)
    # Template shard leaves as (total,) so loaded globals slot in; the
    # restorer only needs the container structure + leaf paths.
    target = jax.tree_util.tree_map_with_path(
        lambda kp, x: jax.ShapeDtypeStruct((sharder.total,), x.dtype)
        if (_is_shard_path(kp) and x.ndim >= 2) else x, target)
    assembled = restore_tree(root, step=step, target=target)
    return sharder.reshard_opt_state(assembled)


class ZeroUpdate(NamedTuple):
    """Bundle the PPO/IMPALA integration threads through the anakin step
    builders: the update callable + the opt-state init/spec halves."""
    sharder: ZeroSharder
    update: Callable
    init_opt: Callable[[Any], Any]
    opt_specs: Any


def build_zero_update(params_template: Any, tx, world: int, *,
                      zero_sharding: str = "opt+grads",
                      quantized: str = "off",
                      axis_name: str = DATA_AXIS,
                      should_shard: Optional[Callable[[str], bool]] = None
                      ) -> ZeroUpdate:
    """One-stop constructor for the RLlib/Train wiring: sharder + update
    fn + opt init/specs, with the memory/wire gauges exported."""
    sharder = ZeroSharder(params_template, world, should_shard=should_shard)
    update = make_update_fn(sharder, tx, axis_name=axis_name,
                            zero_sharding=zero_sharding, quantized=quantized)
    export_zero_metrics(sharder, tx, zero_sharding=zero_sharding,
                        quantized=quantized)
    return ZeroUpdate(sharder, update,
                      lambda params: sharder.init_opt_state(tx, params),
                      sharder.opt_specs(tx))
