"""One async dataflow substrate: bounded pipelined stages.

This repo re-derived the same bounded-in-flight / backpressure / drain
pattern six times by hand — ``mesh_group.InflightWindow``/``StepPipeline``,
``SampleStream`` (rllib/evaluation), ``DevicePrefetcher`` (data/prefetch),
``AsyncCommitter`` (checkpoint/coordinator), the MPMD step window, and the
serving admission loop.  This module is the extraction (the Ray dataflow
thesis, arXiv:1712.05889 §3, and Podracer's actor/learner decoupling,
arXiv:2104.06272): a small operator core every "more in flight" feature
composes from, instead of growing a new subsystem.

Three primitives, one contract each:

- :class:`Window` — the in-flight bookkeeping primitive (bounded deque of
  dispatched-but-undrained items).  Pure data structure, no threads; both
  the mesh StepPipeline and the rollout plane's per-worker fragment
  streams are built on it.
- :class:`Stage` — a bounded thread-chained transform over an item
  iterator: ``workers`` threads pull items from the source, apply ``fn``,
  and push results into a queue of at most ``depth`` items.  Backpressure
  is by construction (a full queue parks the workers; a stage never holds
  more than ``depth`` finished + ``workers`` in-progress items).  Fan-out
  is ``workers > 1``; fan-in ordering is selectable (``ordered=True``
  re-serializes results into source order through a bounded reorder
  buffer, ``ordered=False`` yields completion order).
- :class:`RefStream` — the same bound for driver-side ObjectRef chains: a
  lazy source of *submit thunks* is kept at most ``depth`` refs in flight;
  the driver only ever holds refs, so peak store residency is the window.

Shared semantics:

- **Typed error propagation** — a worker/source exception is delivered to
  the consumer at the failing item's position with its ORIGINAL type and
  traceback (``exc.flow_stage`` names the stage); errors are sticky, never
  silently truncated into StopIteration.
- **Cooperative cancellation / drain** — every operator carries a
  :class:`CancellationToken`.  ``close()`` cancels the token, unblocks
  producers parked on full queues, joins all worker threads (bounded), and
  releases in-flight refs; idempotent and safe from ``__del__``.
  Tokens nest (``child()``), so one ``cancel()`` at the root drains a
  whole pipeline — the gang-restart story (checkpoint AsyncCommitter,
  docs/FAULT_TOLERANCE.md).
- **Free observability** — per-stage ``flow_*`` metrics (items total,
  queue depth/peak, idle fraction, items/s; tagged ``stage=<name>``)
  export through ray_tpu.util.metrics to the dashboard ``/metrics``
  endpoint (best-effort: skipped with no connected driver), and per-item
  profiling spans land in the ray_tpu._private.profiling recorder.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

__all__ = [
    "CancellationToken",
    "FlowCancelled",
    "Window",
    "Stage",
    "RefStream",
    "chain_stages",
]


class FlowCancelled(RuntimeError):
    """Raised to a consumer blocked on a flow that was cancelled."""


class CancellationToken:
    """Cooperative cancellation shared down an operator chain.

    ``cancel()`` is one call and is final; workers poll ``cancelled`` (or
    block on ``wait``) at their loop edges.  ``on_cancel`` callbacks fire
    exactly once, on the cancelling thread.  ``child()`` derives a token
    that cancels with its parent but can also be cancelled alone — a
    pipeline cancels root-down, one stage can still drain solo.
    """

    def __init__(self, parent: Optional["CancellationToken"] = None):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._callbacks: List[Callable[[], None]] = []
        if parent is not None:
            parent.on_cancel(self.cancel)

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            try:
                cb()
            except Exception:
                pass

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block up to ``timeout``; True iff the token is cancelled."""
        return self._event.wait(timeout)

    def on_cancel(self, cb: Callable[[], None]) -> None:
        """Register ``cb`` to run at cancel time (immediately if already
        cancelled)."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb()

    def child(self) -> "CancellationToken":
        return CancellationToken(parent=self)

    def raise_if_cancelled(self) -> None:
        if self._event.is_set():
            raise FlowCancelled("flow cancelled")


class Window:
    """Bounded window of dispatched-but-undrained work — the backpressure
    primitive under the mesh step pipeline, the MPMD microbatch window and
    the rollout plane's per-worker fragment streams: items append at
    dispatch, ``over_depth`` tells the owner to drain the oldest before
    dispatching more, so the producer side always holds queued work while
    the consumer touches a result."""

    __slots__ = ("depth", "_items")

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError(f"window depth must be >= 1, got {depth}")
        self.depth = depth
        self._items: collections.deque = collections.deque()

    def append(self, item) -> None:
        self._items.append(item)

    def popleft(self):
        return self._items.popleft()

    def peek(self):
        return self._items[0]

    def remove(self, item) -> None:
        self._items.remove(item)

    def clear(self) -> list:
        out, self._items = list(self._items), collections.deque()
        return out

    @property
    def over_depth(self) -> bool:
        return len(self._items) > self.depth

    @property
    def full(self) -> bool:
        return len(self._items) >= self.depth

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)


# ---------------------------------------------------------------------------
# Stage: bounded thread-chained transform
# ---------------------------------------------------------------------------

class _End:
    """Producer→consumer end-of-stream sentinel (carries the seq count so
    an ordered consumer knows which gaps are real)."""
    __slots__ = ("seq",)

    def __init__(self, seq: int):
        self.seq = seq


class _Failure:
    """A worker/source exception, delivered at its item's position."""
    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


def _bounded_put(q: "queue.Queue", token: CancellationToken, item) -> bool:
    """Bounded-queue put that aborts promptly on cancel — a producer must
    never be stranded on a full queue the consumer abandoned."""
    while not token.cancelled:
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


class _StageCore:
    """All state shared with worker threads.  Deliberately separate from
    the user-facing Stage: a thread target referencing the Stage itself
    would keep it alive forever, so consumer-side GC could never trigger
    __del__/close and the threads would leak."""

    def __init__(self, name: str, fn, src, depth: int, workers: int,
                 token: CancellationToken, span: Optional[str],
                 sink: bool = False):
        self.name = name
        self.fn = fn
        self.src = src
        self.token = token
        self.span = span
        self.sink = sink
        # Trace context captured at construction (the creator's thread):
        # stage worker threads install it so their spans — and anything
        # they submit — join the creating trace instead of floating.
        self.trace_ctx = None
        try:
            from ray_tpu import observability as obs

            if obs.enabled():
                self.trace_ctx = obs.get_context()
        except Exception:
            pass
        self.out_q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self.src_lock = threading.Lock()
        self.state_lock = threading.Lock()
        self.src_exhausted = False
        self.failed = False
        self.seq = 0
        self.workers_alive = workers
        # stats (updated under state_lock except monotonic counters)
        self.items_in = 0
        self.idle_s = 0.0
        self.busy_s = 0.0
        self.peak_queue = 0

    def close_src(self) -> None:
        close = getattr(self.src, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass


def _stage_worker(core: _StageCore) -> None:
    """Worker thread body (module-level on purpose — see _StageCore)."""
    from ray_tpu._private import profiling

    if core.trace_ctx is not None:
        try:
            from ray_tpu import observability as obs

            obs.set_context(core.trace_ctx)  # fresh thread: nothing saved
        except Exception:
            pass
    try:
        while not core.token.cancelled:
            t_wait0 = time.perf_counter()
            with core.src_lock:
                with core.state_lock:
                    if core.src_exhausted or core.failed:
                        return
                try:
                    item = next(core.src)
                except StopIteration:
                    with core.state_lock:
                        core.src_exhausted = True
                    return
                except BaseException as e:  # noqa: BLE001 — to consumer
                    with core.state_lock:
                        if core.failed:
                            return
                        core.failed = True
                        seq, core.seq = core.seq, core.seq + 1
                    _tag_stage(e, core.name)
                    _bounded_put(core.out_q, core.token, (seq, _Failure(e)))
                    return
                with core.state_lock:
                    seq, core.seq = core.seq, core.seq + 1
                    core.items_in += 1
            t0 = time.perf_counter()
            try:
                out = core.fn(item)
            except BaseException as e:  # noqa: BLE001 — to consumer
                with core.state_lock:
                    core.failed = True
                _tag_stage(e, core.name)
                _bounded_put(core.out_q, core.token, (seq, _Failure(e)))
                return
            t1 = time.perf_counter()
            with core.state_lock:
                core.idle_s += t0 - t_wait0
                core.busy_s += t1 - t0
            if core.span is not None:
                profiling.record_span(core.span, t0, t1, stage=core.name,
                                      seq=seq)
            if core.sink:
                continue  # results are fn's side effects; nothing queues
            with core.state_lock:
                core.peak_queue = max(core.peak_queue, core.out_q.qsize())
            if not _bounded_put(core.out_q, core.token, (seq, out)):
                return
    finally:
        with core.state_lock:
            core.workers_alive -= 1
            last = core.workers_alive == 0
            end_seq = core.seq
        if last:
            # The workers own the source: release its upstream resources
            # (threads, object refs) here, where it is not mid-pull.
            core.close_src()
            if not core.sink:
                _bounded_put(core.out_q, core.token, _End(end_seq))


def _tag_stage(exc: BaseException, name: str) -> None:
    try:
        exc.flow_stage = name
    except Exception:
        pass


class Stage(Iterator[Any]):
    """Bounded-in-flight transform over an item iterator.

    ``fn(item) -> out`` runs on ``workers`` background threads pulling
    from ``source``; results flow through a queue of at most ``depth``
    items.  ``ordered=True`` (default) re-serializes multi-worker results
    into source order; ``ordered=False`` yields them as they complete.
    ``workers=0`` degrades to a threadless inline transform (debugging /
    comparison baseline).  ``sink=True`` makes the stage terminal: ``fn``
    consumes items purely by side effect (resolving futures, writing
    files), nothing queues downstream and the stage is not iterable —
    the request/response shape (e.g. the serve batcher), where callers
    wait on futures ``fn`` resolves rather than pulling an iterator.
    Iterate to consume (non-sink); ``close()`` (also via ``with`` or GC)
    cancels, drains and joins every thread.

    The consumer side is single-threaded by contract (chained stages pull
    from each other under the downstream stage's source lock)."""

    def __init__(self, source: Iterable[Any], fn: Callable[[Any], Any],
                 *, depth: int = 2, workers: int = 1, ordered: bool = True,
                 sink: bool = False, name: str = "stage",
                 token: Optional[CancellationToken] = None,
                 span: Optional[str] = None, export_metrics: bool = True):
        if depth < 1:
            raise ValueError(f"stage depth must be >= 1, got {depth}")
        if workers < 0:
            raise ValueError(f"workers must be >= 0, got {workers}")
        if sink and workers < 1:
            raise ValueError("a sink stage needs at least one worker")
        self.name = name
        self.depth = int(depth)
        self.workers = int(workers)
        self.ordered = bool(ordered)
        self.sink = bool(sink)
        self.token = token if token is not None else CancellationToken()
        self._export = bool(export_metrics)
        self._core = _StageCore(name, fn, iter(source), depth,
                                max(1, workers), self.token,
                                span if span is not None else f"flow_{name}",
                                sink=self.sink)
        self._threads: List[threading.Thread] = []
        self._buffer: Dict[int, Any] = {}   # ordered-mode reorder buffer
        self._next_seq = 0
        self._end_seq: Optional[int] = None
        self._end: Optional[_Failure] = None  # sticky end: error or clean
        self._done = False
        self._consumed = 0
        self._t0 = time.monotonic()
        self._last_export = 0.0
        self._metrics = None
        if self.workers > 0:
            for i in range(self.workers):
                t = threading.Thread(target=_stage_worker,
                                     args=(self._core,), daemon=True,
                                     name=f"rtpu-flow-{name}-{i}")
                self._threads.append(t)
                t.start()

    # ---- consumer side ---------------------------------------------------
    def __iter__(self) -> "Stage":
        return self

    def __next__(self):
        if self.sink:
            raise TypeError(
                f"sink stage {self.name!r} is not iterable — its fn "
                "consumes items by side effect; use close()/join")
        if self._done:
            self._raise_end()
        if self.workers == 0:
            return self._next_inline()
        while True:
            got = self._pop_buffered()
            if got is not None:
                return self._deliver(got)
            if self._end_seq is not None and self._next_seq >= self._end_seq:
                self._finish(None)
            if self._end_seq is not None and \
                    self._core.out_q.empty() and self._threads_dead():
                # Gap before end-of-stream with every worker exited: the
                # item was dropped by a cancelled put.  Treat as end —
                # never hang a consumer.
                self._finish(None)
            try:
                item = self._core.out_q.get(timeout=0.5)
            except queue.Empty:
                if self.token.cancelled and self._core.out_q.empty():
                    # Cancelled workers exit without an _End sentinel
                    # (their puts abort); surface the cancellation, not a
                    # bogus worker-death error.
                    self._finish(_Failure(FlowCancelled(
                        f"flow stage {self.name!r} cancelled")))
                if self._end_seq is None and self._threads_dead():
                    # Workers always enqueue _End in their finally, so
                    # this means a thread was killed hard.
                    self._finish(_Failure(RuntimeError(
                        f"flow stage {self.name!r} worker died")))
                continue
            if isinstance(item, _End):
                self._end_seq = item.seq
                if not self.ordered:
                    # FIFO queue: everything produced was put before _End,
                    # so an unordered consumer has already seen it all.
                    self._finish(None)
                continue
            seq, value = item
            if not self.ordered:
                if isinstance(value, _Failure):
                    self._finish(value)
                return self._deliver(value)
            self._buffer[seq] = value

    def _pop_buffered(self):
        if self.ordered and self._next_seq in self._buffer:
            value = self._buffer.pop(self._next_seq)
            self._next_seq += 1
            if isinstance(value, _Failure):
                self._finish(value)
            return value
        return None

    def _next_inline(self):
        try:
            item = next(self._core.src)
        except StopIteration:
            self._core.close_src()
            self._finish(None)
        except BaseException as e:  # noqa: BLE001
            _tag_stage(e, self.name)
            self._finish(_Failure(e))
        try:
            out = self._core.fn(item)
        except BaseException as e:  # noqa: BLE001
            _tag_stage(e, self.name)
            self._finish(_Failure(e))
        self._core.items_in += 1
        return self._deliver(out)

    def _deliver(self, value):
        self._consumed += 1
        self._maybe_export()
        return value

    def _threads_dead(self) -> bool:
        return bool(self._threads) and \
            not any(t.is_alive() for t in self._threads)

    def _finish(self, failure: Optional[_Failure]):
        """Record the sticky end state and raise it (never returns)."""
        self._done = True
        self._end = failure
        self._export_metrics(final=True)
        self._raise_end()

    def _raise_end(self):
        if self._end is not None:
            raise self._end.error
        raise StopIteration

    # ---- lifecycle -------------------------------------------------------
    def close(self) -> None:
        """Cancel, unblock producers parked on the full queue, join all
        worker threads, release the source.  Idempotent; safe mid-stream
        (pending results are dropped)."""
        self.token.cancel()
        while True:  # unblock producers waiting on a full queue
            try:
                self._core.out_q.get_nowait()
            except queue.Empty:
                break
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []
        self._buffer.clear()
        # Release the source too (idempotent): closing the tail of a
        # chain drains the whole pipeline, joining upstream threads.
        self._core.close_src()
        if not self._done:
            self._done = True
            self._end = None
            self._export_metrics(final=True)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "Stage":
        return self

    def __exit__(self, exc_type, exc_val, tb) -> None:
        self.close()

    # ---- observability ---------------------------------------------------
    @property
    def worker_threads(self) -> List[threading.Thread]:
        """Live worker threads (tests assert none leak past close)."""
        return list(self._threads)

    @property
    def peak_occupancy(self) -> int:
        return max(self._core.peak_queue, len(self._buffer))

    @property
    def items_delivered(self) -> int:
        return self._consumed

    def idle_frac(self) -> float:
        total = self._core.idle_s + self._core.busy_s
        return self._core.idle_s / total if total > 0 else 0.0

    def stats(self) -> Dict[str, Any]:
        dt = time.monotonic() - self._t0
        return {
            "stage": self.name,
            "depth": self.depth,
            "workers": self.workers,
            "items_in": self._core.items_in,
            "items_out": self._consumed,
            "queue_depth": self._core.out_q.qsize(),
            "queue_peak": self.peak_occupancy,
            "idle_frac": self.idle_frac(),
            "items_per_s": self._consumed / dt if dt > 0 else 0.0,
        }

    def _metric_handles(self):
        from ray_tpu.util.metrics import Gauge, Meter

        handles = {
            "items": Meter("flow_items_total",
                           "items delivered by flow stages",
                           tag_keys=("stage",)),
            "depth": Gauge("flow_queue_depth",
                           "current occupancy of a flow stage's queue",
                           tag_keys=("stage",)),
            "peak": Gauge("flow_queue_peak",
                          "peak occupancy of a flow stage's queue",
                          tag_keys=("stage",)),
            "idle": Gauge("flow_idle_frac",
                          "fraction of stage worker time spent waiting "
                          "on upstream", tag_keys=("stage",)),
            "rate": Gauge("flow_items_per_s",
                          "delivered items per second of a flow stage",
                          tag_keys=("stage",)),
        }
        for h in handles.values():
            h.set_default_tags({"stage": self.name})
        return handles

    def _maybe_export(self):
        if not self._export:
            return
        now = time.monotonic()
        if now - self._last_export >= 2.0:
            self._export_metrics()

    def _export_metrics(self, final: bool = False):
        if not self._export:
            return
        self._last_export = time.monotonic()
        try:
            if self._metrics is None:
                self._metrics = self._metric_handles()
            m, st = self._metrics, self.stats()
            m["items"].mark(self._consumed - m["items"].total())
            if final:
                m["items"].flush({"stage": self.name})
            m["depth"].set(float(st["queue_depth"]))
            m["peak"].set(float(st["queue_peak"]))
            m["idle"].set(float(st["idle_frac"]))
            m["rate"].set(float(st["items_per_s"]))
        except Exception:
            self._metrics = None  # no connected driver: stay local


def chain_stages(source: Iterable[Any], *specs, token=None) -> Stage:
    """Compose stages: each spec is ``(fn, kwargs)`` or a bare callable.
    All stages share children of one token, so closing (or cancelling)
    the returned tail stage drains the whole chain."""
    root = token if token is not None else CancellationToken()
    cur: Any = source
    tail: Optional[Stage] = None
    for i, spec in enumerate(specs):
        fn, kw = spec if isinstance(spec, tuple) else (spec, {})
        kw = dict(kw)
        kw.setdefault("name", f"stage{i}")
        tail = Stage(cur, fn, token=root.child(), **kw)
        cur = tail
    if tail is None:
        raise ValueError("chain_stages needs at least one stage spec")
    # Closing the tail cancels the root, which cancels every stage; the
    # worker-owned source hand-off then joins upstream threads in order.
    tail.token = root
    return tail


# ---------------------------------------------------------------------------
# RefStream: bounded in-flight ObjectRef window over a lazy submit source
# ---------------------------------------------------------------------------

class RefStream(Iterator[Any]):
    """Keep at most ``depth`` ObjectRefs in flight from a lazy source of
    submit thunks; yield refs in submission order.

    The driver never holds bytes: a thunk submits one remote task (or
    chain) and returns its output ref; the window bounds how many outputs
    can be store-resident at once (the consumer must drop each yielded
    ref once consumed — exactly the StreamingDataset contract).  No
    threads: submission is non-blocking, so a pull-driven fill is enough
    for full read→transform→consume overlap.

    ``close()`` releases every in-flight ref (best-effort
    ``ray_tpu.cancel`` when ``cancel_refs=True``) — the drain story for
    gang restarts and dead consumers."""

    def __init__(self, thunks: Iterable[Callable[[], Any]], depth: int,
                 *, name: str = "refs",
                 token: Optional[CancellationToken] = None,
                 prime: Iterable[Any] = (), cancel_refs: bool = False,
                 export_metrics: bool = True):
        self.name = name
        self.token = token if token is not None else CancellationToken()
        self.cancel_refs = bool(cancel_refs)
        self._window = Window(depth)
        for ref in prime:
            self._window.append(ref)
        self._thunks = iter(thunks)
        self._exhausted = False
        self._closed = False
        self._export = bool(export_metrics)
        self._metrics = None
        self._t0 = time.monotonic()
        self._last_export = 0.0
        self.submitted = len(self._window)
        self.emitted = 0
        self.peak_in_flight = len(self._window)

    @property
    def depth(self) -> int:
        return self._window.depth

    def _fill(self) -> None:
        while not self._exhausted and not self._window.full:
            if self.token.cancelled:
                return
            try:
                thunk = next(self._thunks)
            except StopIteration:
                self._exhausted = True
                return
            self._window.append(thunk())
            self.submitted += 1
            self.peak_in_flight = max(self.peak_in_flight,
                                      len(self._window))

    def __iter__(self) -> "RefStream":
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        self.token.raise_if_cancelled()
        self._fill()
        if not self._window:
            self._export_metrics(final=True)
            raise StopIteration
        ref = self._window.popleft()
        self.emitted += 1
        self._maybe_export()
        return ref

    def close(self) -> None:
        """Cancel and release all in-flight refs.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.token.cancel()
        pending = self._window.clear()
        if self.cancel_refs and pending:
            import ray_tpu

            for ref in pending:
                try:
                    ray_tpu.cancel(ref)
                except Exception:
                    pass
        del pending
        self._export_metrics(final=True)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "RefStream":
        return self

    def __exit__(self, exc_type, exc_val, tb) -> None:
        self.close()

    # ---- observability ---------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        dt = time.monotonic() - self._t0
        return {
            "stage": self.name,
            "depth": self.depth,
            "in_flight": len(self._window),
            "peak_in_flight": self.peak_in_flight,
            "submitted": self.submitted,
            "items_out": self.emitted,
            "items_per_s": self.emitted / dt if dt > 0 else 0.0,
        }

    def _maybe_export(self):
        if not self._export:
            return
        if time.monotonic() - self._last_export >= 2.0:
            self._export_metrics()

    def _export_metrics(self, final: bool = False):
        if not self._export:
            return
        self._last_export = time.monotonic()
        try:
            from ray_tpu.util.metrics import Gauge, Meter

            if self._metrics is None:
                items = Meter("flow_items_total",
                              "items delivered by flow stages",
                              tag_keys=("stage",))
                depth = Gauge("flow_queue_depth",
                              "current occupancy of a flow stage's queue",
                              tag_keys=("stage",))
                peak = Gauge("flow_queue_peak",
                             "peak occupancy of a flow stage's queue",
                             tag_keys=("stage",))
                rate = Gauge("flow_items_per_s",
                             "delivered items per second of a flow stage",
                             tag_keys=("stage",))
                for h in (items, depth, peak, rate):
                    h.set_default_tags({"stage": self.name})
                self._metrics = {"items": items, "depth": depth,
                                 "peak": peak, "rate": rate}
            m, st = self._metrics, self.stats()
            m["items"].mark(self.emitted - m["items"].total())
            if final:
                m["items"].flush({"stage": self.name})
            m["depth"].set(float(st["in_flight"]))
            m["peak"].set(float(st["peak_in_flight"]))
            m["rate"].set(float(st["items_per_s"]))
        except Exception:
            self._metrics = None
