"""Device meshes with named logical axes.

The accelerator unit in this framework is a *mesh*, not a device (see
package docstring).  A MeshSpec names the parallelism axes and solves their
sizes against the available devices; `make_mesh` materializes a
jax.sharding.Mesh laid out so that the innermost axes map to adjacent
devices (ICI neighbours on real TPU topologies, where jax's device order
follows the torus).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Canonical axis order: outer (slow, DCN-friendly) → inner (fast, ICI).
# data-parallel outermost, model/tensor innermost — the layout the scaling
# playbook prescribes so tensor-parallel collectives ride nearest-neighbour
# ICI links.
CANONICAL_AXES = ("pipe", "data", "fsdp", "expert", "sequence", "model")


@dataclass
class MeshSpec:
    """Named axis sizes; -1 means "absorb remaining devices" (≤ one axis)."""

    axes: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        for name in self.axes:
            if name not in CANONICAL_AXES:
                raise ValueError(
                    f"unknown mesh axis {name!r}; valid: {CANONICAL_AXES}")
        if sum(1 for v in self.axes.values() if v == -1) > 1:
            raise ValueError("at most one axis may be -1")

    def solve(self, num_devices: int) -> "MeshSpec":
        sizes = dict(self.axes)
        known = 1
        wild = None
        for k, v in sizes.items():
            if v == -1:
                wild = k
            else:
                known *= v
        if wild is not None:
            if num_devices % known:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild] = num_devices // known
        else:
            total = int(np.prod(list(sizes.values()))) if sizes else 1
            if total != num_devices:
                raise ValueError(
                    f"mesh {sizes} needs {total} devices, have {num_devices}")
        return MeshSpec(sizes)

    def ordered(self) -> List[Tuple[str, int]]:
        return [(a, self.axes[a]) for a in CANONICAL_AXES if a in self.axes]

    @property
    def size(self) -> int:
        return int(np.prod([v for _, v in self.ordered()])) if self.axes else 1


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a jax.sharding.Mesh from a (solved) spec.

    Axis order in the device array follows CANONICAL_AXES so the last axes
    are nearest-neighbour on the ICI torus."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if -1 not in spec.axes.values() and 0 < spec.size <= len(devs):
        devs = devs[: spec.size]  # smaller meshes use a device subset
    spec = spec.solve(len(devs))
    names = [a for a, _ in spec.ordered()]
    shape = [s for _, s in spec.ordered()]
    if not names:
        names, shape = ["data"], [len(devs)]
    arr = np.array(devs[: int(np.prod(shape))]).reshape(shape)
    return Mesh(arr, axis_names=tuple(names))


def local_mesh(**axes) -> "object":
    """Convenience: mesh over this process's local devices.

    local_mesh(data=-1) → pure DP; local_mesh(data=2, model=4) → DP×TP."""
    return make_mesh(MeshSpec(axes))


def host_local_device_count() -> int:
    import jax

    return jax.local_device_count()
