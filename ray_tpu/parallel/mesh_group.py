"""MeshGroup: the gang-scheduled TPU process-group primitive.

The keystone between the actor core and the SPMD layer (SURVEY §7 step 3):
a placement group reserves one bundle per TPU host, one accelerator-visible
actor is spawned in each bundle, and the actors rendezvous through
``jax.distributed.initialize`` (rank 0 hosts the coordinator) so that every
host's local chips join ONE global jax mesh.  After bootstrap, ``run(fn)``
fans the same function out to every host process — the multi-controller SPMD
model hidden behind a single driver-side handle.

This unifies and replaces, TPU-style, the reference's two bootstrap paths:
Train's BackendExecutor placement-group + process-group setup
(python/ray/train/_internal/backend_executor.py:43-315,
train/torch/config.py:69-121) and the collective library's NCCLUniqueID
named-actor rendezvous (python/ray/util/collective/util.py:9,
collective_group/nccl_collective_group.py:28-100).  Both Train's JaxBackend
and RLlib's learner group bootstrap through the same helpers here.

Test strategy: on CPU, a group of N single-process actors each exposing K
virtual devices (``--xla_force_host_platform_device_count``) forms an
N*K-device global mesh with gloo cross-process collectives — the JAX
equivalent of the reference's _fake_gpus mode, exercised in
tests/test_mesh_group.py.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import ray_tpu


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _coordinator_address(loopback: bool = False) -> str:
    """Pick ``ip:port`` for the jax.distributed coordinator.

    MUST run *inside the rank-0 worker process* (the reference allocates the
    process-group port the same way: get_address_and_port executes on worker
    0, python/ray/train/_internal/utils.py): the port has to be free on rank
    0's machine, and the address has to be one the other hosts can route to
    — neither is true of a port probed on the driver or of the driver's view
    of rank 0's hostname."""
    from ray_tpu._private.transfer import routable_ip

    port = _free_port()
    if loopback:
        return f"127.0.0.1:{port}"
    return f"{routable_ip()}:{port}"


def force_host_device_count(flags: str, n: int) -> str:
    """Return XLA_FLAGS with --xla_force_host_platform_device_count pinned
    to n, replacing (not merely appending to) any inherited value."""
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   flags or "")
    return (flags + f" --xla_force_host_platform_device_count={n}").strip()


def bootstrap_jax_distributed(coordinator: str, world_size: int, rank: int,
                              platform: Optional[str] = None,
                              local_device_count: Optional[int] = None) -> dict:
    """Runs inside each mesh-worker process, before any jax backend touch.

    Sets the platform + virtual-device flags, then joins the
    jax.distributed rendezvous; afterwards ``jax.devices()`` spans the whole
    group.  On CPU the cross-process collective backend is gloo (the
    in-graph XLA collectives then work exactly as they do over ICI).
    A world of 1 needs no rendezvous: only the platform/device-count setup
    runs (so single-worker training works on reused pooled workers)."""
    import os

    if local_device_count:
        os.environ["XLA_FLAGS"] = force_host_device_count(
            os.environ.get("XLA_FLAGS", ""), local_device_count)
    if platform:
        os.environ["JAX_PLATFORMS"] = platform

    import jax

    if world_size > 1:
        try:
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                raise RuntimeError(
                    "mesh worker's jax backend was initialized before "
                    "bootstrap (the worker ran jax code earlier); a "
                    "multi-host MeshGroup requires fresh worker processes")
        except ImportError:  # private API moved — proceed optimistically
            pass
    if platform:
        try:
            jax.config.update("jax_platforms", platform)
        except RuntimeError:
            pass
    if world_size > 1:
        if (platform or "").startswith("cpu"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=world_size,
                                   process_id=rank)
    return {"rank": rank,
            "process_index": jax.process_index(),
            "local_devices": jax.local_device_count(),
            "global_devices": jax.device_count()}


@ray_tpu.remote
class MeshWorker:
    """One host process of a mesh group.  Carries a state dict so stateful
    users (learners, inference replicas) can pin objects host-side."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.state: Dict[str, Any] = {}

    def node_info(self) -> dict:
        import os
        import socket

        return {"rank": self.rank, "pid": os.getpid(),
                "host": socket.gethostname()}

    def setup_env(self, env: Dict[str, str]):
        import os

        os.environ.update(env)
        return True

    def bootstrap(self, coordinator: str, platform: Optional[str],
                  local_device_count: Optional[int]) -> dict:
        return bootstrap_jax_distributed(
            coordinator, self.world_size, self.rank, platform,
            local_device_count)

    def run(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def run_stateful(self, fn: Callable, *args, **kwargs):
        """fn(state_dict, *args) — for building/using host-pinned state."""
        return fn(self.state, *args, **kwargs)


def rendezvous(workers: Sequence, platform: Optional[str] = None,
               local_device_count: Optional[int] = None,
               timeout: float = 120.0) -> List[dict]:
    """Bootstrap jax.distributed across an existing gang of actors.

    Workers must expose node_info/setup_env and either bootstrap() (native
    MeshWorker) or execute() (Train's TrainWorker) — this is the piece
    BackendExecutor delegates to.  Returns per-rank device info."""
    world = len(workers)
    infos = ray_tpu.get([w.node_info.remote() for w in workers],
                        timeout=timeout)
    hosts = {i["host"] for i in infos}
    # Allocate the coordinator ip:port ON rank 0 (not the driver): the port
    # must be free on rank 0's machine and the ip routable from the other
    # hosts.  MeshWorker exposes run(); Train's TrainWorker exposes execute().
    w0 = workers[0]
    caller = w0.run if hasattr(w0, "run") else w0.execute
    coordinator = ray_tpu.get(
        caller.remote(_coordinator_address, len(hosts) == 1), timeout=timeout)
    env = {"RTPU_COORDINATOR": coordinator, "RTPU_WORLD_SIZE": str(world)}
    ray_tpu.get([w.setup_env.remote({**env, "RTPU_RANK": str(rank)})
                 for rank, w in enumerate(workers)], timeout=timeout)
    calls = []
    for rank, w in enumerate(workers):
        if hasattr(w, "bootstrap"):
            calls.append(w.bootstrap.remote(coordinator, platform,
                                            local_device_count))
        else:
            calls.append(w.execute.remote(
                bootstrap_jax_distributed, coordinator, world, rank,
                platform, local_device_count))
    return ray_tpu.get(calls, timeout=timeout)


class MeshGroup:
    """A gang of one actor per TPU host forming one global jax mesh.

    ``MeshGroup(2, platform="cpu", local_device_count=2)`` on one machine
    builds a 4-device virtual mesh across 2 processes; on real hardware,
    ``MeshGroup(num_hosts, resources_per_host={"TPU": 4})`` gangs the pod.
    """

    def __init__(self, num_hosts: int,
                 resources_per_host: Optional[Dict[str, float]] = None,
                 platform: Optional[str] = None,
                 local_device_count: Optional[int] = None,
                 strategy: str = "PACK",
                 bootstrap_timeout: float = 120.0):
        self.num_hosts = num_hosts
        self.platform = platform
        self.local_device_count = local_device_count
        res = dict(resources_per_host or {"CPU": 1.0})
        self.pg = None
        opts: Dict[str, Any] = {"max_concurrency": 2}
        if res.get("CPU"):
            opts["num_cpus"] = res["CPU"]
        if res.get("TPU"):
            opts["num_tpus"] = res["TPU"]
        extra = {k: v for k, v in res.items() if k not in ("CPU", "TPU")}
        if extra:
            opts["resources"] = extra
        if num_hosts > 1:
            from ray_tpu.util import PlacementGroupSchedulingStrategy
            from ray_tpu.util.placement_group import placement_group

            self.pg = placement_group([dict(res) for _ in range(num_hosts)],
                                      strategy=strategy)
            self.pg.ready(timeout=bootstrap_timeout)
            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                self.pg)
        self.workers = [MeshWorker.options(**opts).remote(rank, num_hosts)
                        for rank in range(num_hosts)]
        self.device_info = rendezvous(self.workers, platform,
                                      local_device_count,
                                      timeout=bootstrap_timeout)

    @property
    def global_device_count(self) -> int:
        return self.device_info[0]["global_devices"]

    def run(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Fan fn out to every host process; returns per-rank results."""
        return ray_tpu.get([w.run.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def run_async(self, fn: Callable, *args, **kwargs):
        return [w.run.remote(fn, *args, **kwargs) for w in self.workers]

    def run_stateful(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get([w.run_stateful.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def run_rank(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(self.workers[rank].run.remote(fn, *args, **kwargs))

    def run_rank_stateful(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.workers[rank].run_stateful.remote(fn, *args, **kwargs))

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None
