"""MeshGroup: the gang-scheduled TPU process-group primitive.

The keystone between the actor core and the SPMD layer (SURVEY §7 step 3):
a placement group reserves one bundle per TPU host, one accelerator-visible
actor is spawned in each bundle, and the actors rendezvous through
``jax.distributed.initialize`` (rank 0 hosts the coordinator) so that every
host's local chips join ONE global jax mesh.  After bootstrap, ``run(fn)``
fans the same function out to every host process — the multi-controller SPMD
model hidden behind a single driver-side handle.

This unifies and replaces, TPU-style, the reference's two bootstrap paths:
Train's BackendExecutor placement-group + process-group setup
(python/ray/train/_internal/backend_executor.py:43-315,
train/torch/config.py:69-121) and the collective library's NCCLUniqueID
named-actor rendezvous (python/ray/util/collective/util.py:9,
collective_group/nccl_collective_group.py:28-100).  Both Train's JaxBackend
and RLlib's learner group bootstrap through the same helpers here.

Fault tolerance
===============
SPMD gangs fail as a unit: every rank participates in one
``jax.distributed`` world, so a single dead host leaves the survivors
blocked inside a collective that can never complete.  The supervisor layer
here (the Podracer gang-failure model; reference analogue: Train's
BackendExecutor failure handling + RLlib's fault-tolerant actor manager):

- **Eager rank-death detection** — ``run()`` resolves its per-rank futures
  through :func:`gang_get`, which polls with ``ray_tpu.wait`` instead of a
  blocking ``get``: the moment any rank's future resolves to an
  actor/worker-death error, the peers are abandoned (they are poisoned
  anyway) and a typed :class:`ray_tpu.exceptions.MeshGroupError` carrying
  ``failed_ranks`` is raised — no indefinite hang on a dead collective.
- **Health probing** — ``health_check(deadline)`` pings every rank with a
  deadline (``MeshWorker.ping`` runs on the actor's second concurrency
  slot, so it answers even while a training step is in flight) and raises
  ``MeshGroupError`` naming the unresponsive ranks.
- **Gang restart** — one dead rank invalidates the whole world, so
  recovery is all-or-nothing: ``_restart()`` tears down every worker and
  the placement group, re-spawns fresh processes (a stale jax backend
  cannot re-rendezvous), and re-runs the rendezvous.  ``run()`` drives
  this automatically under a ``max_group_restarts`` budget with
  exponential backoff; restart counts are exported through
  ``ray_tpu.util.metrics`` (``mesh_group_restarts_total``,
  ``mesh_group_restart_failures_total``).
- **Recovery hooks** — ``run(fn, on_restart=...)`` calls
  ``on_restart(group)`` after each successful gang rebuild, before ``fn``
  is retried, so stateful users (e.g. RLlib's DistributedLearnerGroup)
  re-materialize host-pinned state and re-broadcast weights.
- **Deterministic chaos** — ``ray_tpu._private.chaos`` provides
  ``kill_mesh_rank`` (driver-side, seeded) and a schedule-driven in-worker
  killer (env ``RAY_TPU_TESTING_KILL_SCHEDULE`` =
  ``"<op>:<rank>:<nth>[:<generation>]"``; the ``mesh_run`` op fires at
  ``MeshWorker.run`` entry).  Each gang incarnation exports its
  generation via ``RTPU_MESH_GENERATION`` so a schedule can kill exactly
  one incarnation and let the restarted gang survive — the whole
  kill/detect/restart/resume loop is testable on CPU with virtual
  devices (tests/test_mesh_fault_tolerance.py).

Pipelined dispatch (the zero-sync hot path)
===========================================
``run()`` is lockstep: dispatch → block on gang_get → dispatch.  Every
step therefore pays a full driver→worker RPC round trip during which the
accelerators idle — the dominant stall once the step itself is fast.
:class:`StepPipeline` (``group.pipeline()`` / ``group.run_pipelined()``)
removes the driver from the per-step critical path, the Podracer/Sebulba
"keep work enqueued ahead of completion" model (arXiv:2104.06272):

- **Bounded in-flight window** — ``submit(fn, *args)`` dispatches step N
  to every rank immediately and only then drains the oldest step once
  more than ``depth`` are in flight, so the workers always hold the next
  step(s) queued before the driver touches a result (at most ``depth``
  steps remain in flight after submit returns; ``depth + 1`` transiently
  during the backpressure drain).  Results are drained strictly in step
  order through :func:`gang_get`, so PR 1's eager rank-death detection
  fires mid-window exactly as it does in lockstep mode.
- **Device-resident carry** — step functions run in the ``run_stateful``
  shape (``fn(state, *args)``): weights/optimizer state live in the
  worker's state dict as device arrays and never round-trip through the
  driver.  Workers execute pipeline steps strictly in submission order
  (a per-actor sequence gate), so carry mutation is race-free even though
  the actor pool is concurrent.
- **Sparse metrics fetch** — only every ``metrics_interval``-th step
  returns its metrics (host-converted worker-side); the rest reply
  ``None``, so no device→host fetch and no payload serialization gates
  the in-between steps.
- **Restart + replay** — a rank death mid-window raises
  ``MeshGroupError`` eagerly; with ``max_group_restarts > 0`` the gang is
  rebuilt, ``on_restart(group)`` re-materializes carry state, and the
  (bounded, still-held) in-flight window is resubmitted from the oldest
  undrained step — exactly-once carry semantics when the caller
  checkpoints at drain cadence (see docs/PERFORMANCE.md).
- **Observability** — ``driver_sync_count()`` counts blocking per-step
  driver↔worker syncs (the lockstep ``run*`` paths); the pipelined path
  performs zero and tests assert that.  Pipeline depth / in-flight
  occupancy / dispatch+drain latency export through
  ``ray_tpu.util.metrics`` and the span recorder in
  ``ray_tpu._private.profiling``.

Test strategy: on CPU, a group of N single-process actors each exposing K
virtual devices (``--xla_force_host_platform_device_count``) forms an
N*K-device global mesh with gloo cross-process collectives — the JAX
equivalent of the reference's _fake_gpus mode, exercised in
tests/test_mesh_group.py (pipeline semantics: tests/test_step_pipeline.py).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.parallel import flow

# Errors that poison the gang (vs. a user exception raised by fn, which is
# re-raised as-is: the worker is alive and a restart would not help).
# RpcTimeoutError counts: a rank whose control-plane edge blew its
# deadline is indistinguishable from a hung rank — the supervisor must
# treat it as failed (restart path) rather than assume the reply will
# eventually arrive (replies either arrive or the process died is no
# longer the plane's contract; deadlines are).
_GANG_ERRORS = (exc.ActorDiedError, exc.ActorUnavailableError,
                exc.WorkerCrashedError, exc.ObjectLostError,
                exc.RpcTimeoutError)

# The recurring CPU-gloo TCP race: a rank's connection pair aborts
# mid-collective ("gloo::EnforceNotMet ... op.preamble.length",
# "Connection reset by peer", ...).  The worker processes are alive and
# the jax program is correct — the *transport* hiccuped — so this failure
# class gets its own bounded in-place recovery (init retry + warm-up +
# same-size rebuild budget) instead of consuming the caller's
# gang-restart/FailureConfig budget.  Matching is textual because gloo
# surfaces the abort as a plain RuntimeError inside the worker.
_TRANSPORT_MARKERS = ("preamble", "connection reset", "connection closed",
                      "connection refused", "enforcenotmet", "timed out",
                      "socket")


def _transport_text(s: str) -> bool:
    s = s.lower()
    if "gloo" not in s and "enforcenotmet" not in s:
        return False
    return any(m in s for m in _TRANSPORT_MARKERS)


def is_transport_abort(err: Any) -> bool:
    """True when ``err`` is (or wraps, rank-for-rank) the gloo TCP
    transport abort rather than a real rank death.  A ``MeshGroupError``
    counts only when EVERY failed rank classifies as transport — one
    genuinely dead rank makes the whole gang failure a death."""
    if getattr(err, "transport_abort", False):
        return True
    if isinstance(err, exc.MeshGroupError):
        ranks = getattr(err, "failed_ranks", None) or {}
        return bool(ranks) and all(is_transport_abort(e)
                                   for e in ranks.values())
    return _transport_text(str(err))

# Driver-side sync counter: every blocking per-step driver↔worker round
# trip on a dispatch path (the lockstep run*/health_check calls) bumps it.
# The pipelined path must leave it untouched — tests assert the delta is
# zero across a pipelined run (the "zero-sync hot path" invariant).
_DRIVER_SYNCS = {"count": 0}


def driver_sync_count() -> int:
    """Blocking driver↔worker syncs performed by lockstep dispatch paths
    since process start.  A pipelined step stream adds zero."""
    return _DRIVER_SYNCS["count"]


def _note_driver_sync() -> None:
    _DRIVER_SYNCS["count"] += 1


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _coordinator_address(loopback: bool = False) -> str:
    """Pick ``ip:port`` for the jax.distributed coordinator.

    MUST run *inside the rank-0 worker process* (the reference allocates the
    process-group port the same way: get_address_and_port executes on worker
    0, python/ray/train/_internal/utils.py): the port has to be free on rank
    0's machine, and the address has to be one the other hosts can route to
    — neither is true of a port probed on the driver or of the driver's view
    of rank 0's hostname."""
    from ray_tpu._private.transfer import routable_ip

    port = _free_port()
    if loopback:
        return f"127.0.0.1:{port}"
    return f"{routable_ip()}:{port}"


def force_host_device_count(flags: str, n: int) -> str:
    """Return XLA_FLAGS with --xla_force_host_platform_device_count pinned
    to n, replacing (not merely appending to) any inherited value."""
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   flags or "")
    return (flags + f" --xla_force_host_platform_device_count={n}").strip()


def bootstrap_jax_distributed(coordinator: str, world_size: int, rank: int,
                              platform: Optional[str] = None,
                              local_device_count: Optional[int] = None) -> dict:
    """Runs inside each mesh-worker process, before any jax backend touch.

    Sets the platform + virtual-device flags, then joins the
    jax.distributed rendezvous; afterwards ``jax.devices()`` spans the whole
    group.  On CPU the cross-process collective backend is gloo (the
    in-graph XLA collectives then work exactly as they do over ICI).
    A world of 1 needs no rendezvous: only the platform/device-count setup
    runs (so single-worker training works on reused pooled workers)."""
    import os

    if local_device_count:
        os.environ["XLA_FLAGS"] = force_host_device_count(
            os.environ.get("XLA_FLAGS", ""), local_device_count)
    if platform:
        os.environ["JAX_PLATFORMS"] = platform

    import jax

    if world_size > 1:
        try:
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                raise RuntimeError(
                    "mesh worker's jax backend was initialized before "
                    "bootstrap (the worker ran jax code earlier); a "
                    "multi-host MeshGroup requires fresh worker processes")
        except ImportError:  # private API moved — proceed optimistically
            pass
    if platform:
        try:
            jax.config.update("jax_platforms", platform)
        except RuntimeError:
            pass
    if world_size > 1:
        if (platform or "").startswith("cpu"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # The gloo TCP rendezvous sporadically aborts while the pairs
        # connect (root cause of the op.preamble.length failures seen
        # mid-update): before any backend is touched the initialize is
        # safely repeatable, so retry it in place instead of paying a
        # full gang teardown.
        retries = int(os.environ.get("RAY_TPU_GLOO_INIT_RETRIES", "2"))
        for attempt in range(retries + 1):
            try:
                jax.distributed.initialize(coordinator_address=coordinator,
                                           num_processes=world_size,
                                           process_id=rank)
                break
            except Exception as e:  # noqa: BLE001 — classified below
                if attempt >= retries or not _transport_text(str(e)):
                    raise
                try:
                    jax.distributed.shutdown()
                except Exception:
                    pass
                time.sleep(0.2 * (attempt + 1))
        if os.environ.get("RAY_TPU_GLOO_WARMUP", "1") != "0":
            _collective_warmup()
    return {"rank": rank,
            "process_index": jax.process_index(),
            "local_devices": jax.local_device_count(),
            "global_devices": jax.device_count()}


def _collective_warmup() -> None:
    """Force every gloo pair to establish NOW, inside the rendezvous, by
    running one tiny cross-process all-reduce.  Connection-time races
    (the other half of the op.preamble.length root cause) then surface
    here — where the supervisor's in-place rendezvous retry can respawn
    the gang cheaply — instead of aborting the first real training step."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    devs = jax.devices()
    if len(devs) <= jax.local_device_count():
        return  # single-process world: nothing to connect
    mesh = Mesh(np.asarray(devs), ("warmup",))
    n = len(devs)
    host = np.arange(n, dtype=np.float32)
    x = jax.make_array_from_callback(
        (n,), NamedSharding(mesh, P("warmup")),
        lambda idx, _a=host: _a[idx])
    out = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
    expect = float(n * (n - 1) / 2)
    got = float(jax.device_get(out))
    if got != expect:
        raise RuntimeError(
            f"collective warm-up all-reduce returned {got}, "
            f"expected {expect}: the gloo group is mis-wired")


def _metrics_to_host(out):
    """Host-convert a step's metrics payload in ONE batched device fetch
    (jax arrays → numpy scalars/arrays); non-jax payloads pass through.
    Runs worker-side only on fetch steps, so the in-between steps never
    pay a device→host transfer or a payload pickle."""
    try:
        import jax
    except ImportError:
        return out
    try:
        return jax.device_get(out)
    except Exception:
        return out


@ray_tpu.remote
class MeshWorker:
    """One host process of a mesh group.  Carries a state dict so stateful
    users (learners, inference replicas) can pin objects host-side."""

    def __init__(self, rank: int, world_size: int, generation: int = 0):
        import os
        import threading

        from ray_tpu._private import chaos

        self.rank = rank
        self.world_size = world_size
        self.generation = generation
        self.state: Dict[str, Any] = {}
        # Pipeline sequence gate: the actor pool runs methods on N threads,
        # so queued pipeline_step calls could otherwise race on the carry
        # state or execute out of order.  Steps wait here for their index.
        self._pipe_cv = threading.Condition()
        self._pipe_next = 0
        self._pipe_err: Optional[str] = None
        os.environ[chaos.GENERATION_ENV] = str(generation)

    def node_info(self) -> dict:
        import os
        import socket

        return {"rank": self.rank, "pid": os.getpid(),
                "host": socket.gethostname()}

    def ping(self) -> int:
        """Cheap liveness probe; runs on the actor's spare concurrency
        slot, so it answers even mid-run()."""
        return self.rank

    def setup_env(self, env: Dict[str, str]):
        import os

        os.environ.update(env)
        return True

    def bootstrap(self, coordinator: str, platform: Optional[str],
                  local_device_count: Optional[int]) -> dict:
        return bootstrap_jax_distributed(
            coordinator, self.world_size, self.rank, platform,
            local_device_count)

    def run(self, fn: Callable, *args, **kwargs):
        from ray_tpu._private import chaos

        chaos.maybe_die("mesh_run", self.rank)
        return fn(*args, **kwargs)

    def run_stateful(self, fn: Callable, *args, **kwargs):
        """fn(state_dict, *args) — for building/using host-pinned state."""
        from ray_tpu._private import chaos

        chaos.maybe_die("mesh_run", self.rank)
        return fn(self.state, *args, **kwargs)

    # ---- pipelined step stream (driven by StepPipeline) ----
    def pipeline_seek(self, next_step: int) -> int:
        """(Re)arm the sequence gate: the next pipeline_step this worker
        executes is ``next_step``.  Called at pipeline creation and after
        a gang restart (fresh processes start at 0, but the replay resumes
        from the oldest undrained step)."""
        with self._pipe_cv:
            self._pipe_next = int(next_step)
            self._pipe_err = None
            self._pipe_cv.notify_all()
        return self.rank

    def pipeline_step(self, step: int, fetch: bool, fn: Callable,
                      *args, **kwargs):
        """Execute one pipelined step in strict submission order.

        ``fn(state, *args)`` — the run_stateful shape: carry lives in the
        state dict as device arrays.  Steps queued ahead of their turn
        park on the sequence gate (they occupy actor-pool threads, which
        is why MeshGroup sizes max_concurrency to pipeline_depth + 2 —
        ping keeps a free slot).  Only ``fetch`` steps return metrics
        (host-converted here, one batched device_get); the rest reply
        None so nothing crosses the wire."""
        from ray_tpu._private import chaos

        deadline = time.monotonic() + 3600.0
        with self._pipe_cv:
            while self._pipe_err is None and step != self._pipe_next:
                if step < self._pipe_next:
                    raise RuntimeError(
                        f"stale pipeline step {step} (worker already at "
                        f"{self._pipe_next}); was the pipeline re-seeked?")
                if not self._pipe_cv.wait(timeout=5.0) and \
                        time.monotonic() > deadline:
                    raise RuntimeError(
                        f"pipeline step {step} stalled waiting for step "
                        f"{self._pipe_next} to complete")
            if self._pipe_err is not None:
                raise RuntimeError(
                    f"pipeline aborted by earlier failure: {self._pipe_err}")
        chaos.maybe_die("pipeline_step", self.rank)
        try:
            out = fn(self.state, *args, **kwargs)
        except BaseException as e:
            # Poison the gate: later queued steps fail fast instead of
            # running against a carry the failed step left half-updated.
            with self._pipe_cv:
                self._pipe_err = f"step {step}: {type(e).__name__}: {e}"
                self._pipe_cv.notify_all()
            raise
        with self._pipe_cv:
            self._pipe_next = step + 1
            self._pipe_cv.notify_all()
        return _metrics_to_host(out) if fetch else None


def gang_get(futures: Sequence, timeout: Optional[float] = None,
             poll_interval: float = 0.25) -> List[Any]:
    """Resolve a gang's per-rank futures with eager failure detection.

    A plain ``ray_tpu.get(list)`` resolves rank 0 first: if rank 0 is a
    survivor stuck in a collective poisoned by a dead peer, the driver
    blocks forever.  This polls ALL futures via ``wait``; as soon as any
    rank resolves to a gang-poisoning error (actor/worker death), a
    ``MeshGroupError(failed_ranks=...)`` is raised immediately and the
    remaining futures are abandoned.  A user exception (``TaskError``) is
    re-raised as-is — the gang is healthy, restart would not help.
    ``timeout`` bounds the whole fan-out; unresolved ranks at the deadline
    are reported in ``failed_ranks`` as ``GetTimeoutError``."""
    remaining: List[tuple] = list(enumerate(futures))  # (rank, ref)
    results: Dict[int, Any] = {}
    failed: Dict[int, BaseException] = {}
    deadline = None if timeout is None else time.monotonic() + timeout
    while remaining:
        refs = [r for _, r in remaining]
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                timeout=poll_interval)
        ready_ids = {id(r) for r in ready}
        still: List[tuple] = []
        for rank, ref in remaining:
            if id(ref) not in ready_ids:
                still.append((rank, ref))
                continue
            try:
                results[rank] = ray_tpu.get(ref)
            except _GANG_ERRORS as e:
                failed[rank] = e
            except exc.RayTpuError as e:
                # A gloo transport abort surfaces as a TaskError whose
                # message names the race; it poisons the gang exactly like
                # a rank death (peers are stuck in the collective), so it
                # joins failed_ranks — tagged so supervisors can charge
                # the transport budget instead of the restart budget.
                if not _transport_text(str(e)):
                    raise  # user exception: gang is not poisoned
                failed[rank] = e
        remaining = still
        if failed:
            _abandon(remaining)
            err = exc.MeshGroupError("mesh rank(s) died mid-run",
                                     failed_ranks=failed)
            err.transport_abort = all(is_transport_abort(e)
                                      for e in failed.values())
            raise err
        if deadline is not None and remaining and time.monotonic() > deadline:
            late = {rank: exc.GetTimeoutError(
                f"rank {rank} produced no result within {timeout}s")
                for rank, _ in remaining}
            _abandon(remaining)
            raise exc.MeshGroupError("mesh rank(s) missed the deadline",
                                     failed_ranks=late)
    return [results[rank] for rank in range(len(futures))]


def _abandon(remaining) -> None:
    """Best-effort cancel of the poisoned peers' futures: queued-but-not-
    started calls are dropped; in-flight collective work is unrecoverable
    anyway and dies with the gang teardown."""
    for _, ref in remaining:
        try:
            ray_tpu.cancel(ref)
        except Exception:
            pass


def rendezvous(workers: Sequence, platform: Optional[str] = None,
               local_device_count: Optional[int] = None,
               timeout: float = 120.0) -> List[dict]:
    """Bootstrap jax.distributed across an existing gang of actors.

    Workers must expose node_info/setup_env and either bootstrap() (native
    MeshWorker) or execute() (Train's TrainWorker) — this is the piece
    BackendExecutor delegates to.  Returns per-rank device info."""
    world = len(workers)
    infos = ray_tpu.get([w.node_info.remote() for w in workers],
                        timeout=timeout)
    hosts = {i["host"] for i in infos}
    # Allocate the coordinator ip:port ON rank 0 (not the driver): the port
    # must be free on rank 0's machine and the ip routable from the other
    # hosts.  MeshWorker exposes run(); Train's TrainWorker exposes execute().
    w0 = workers[0]
    caller = w0.run if hasattr(w0, "run") else w0.execute
    coordinator = ray_tpu.get(
        caller.remote(_coordinator_address, len(hosts) == 1), timeout=timeout)
    env = {"RTPU_COORDINATOR": coordinator, "RTPU_WORLD_SIZE": str(world)}
    ray_tpu.get([w.setup_env.remote({**env, "RTPU_RANK": str(rank)})
                 for rank, w in enumerate(workers)], timeout=timeout)
    calls = []
    for rank, w in enumerate(workers):
        if hasattr(w, "bootstrap"):
            calls.append(w.bootstrap.remote(coordinator, platform,
                                            local_device_count))
        else:
            calls.append(w.execute.remote(
                bootstrap_jax_distributed, coordinator, world, rank,
                platform, local_device_count))
    # The rendezvous itself is a collective: a rank dying inside
    # jax.distributed.initialize would otherwise hang the peers (and the
    # driver) forever.
    return gang_get(calls, timeout=timeout)


def _restart_metrics():
    """Lazy metric handles (internal_kv needs a connected driver)."""
    from ray_tpu.util.metrics import Counter

    return (Counter("mesh_group_restarts_total",
                    "successful MeshGroup gang restarts"),
            Counter("mesh_group_restart_failures_total",
                    "failed MeshGroup gang-restart attempts"))


# The bounded in-flight window primitive was extracted to the shared
# dataflow substrate (parallel/flow.py) along with the rest of the
# backpressure/drain machinery; re-exported here because the step
# pipeline's public docs and downstream code name it InflightWindow.
InflightWindow = flow.Window


class _InflightStep:
    """One dispatched-but-undrained step: the per-rank futures plus the
    spec needed to resubmit it after a gang restart (the window is bounded
    by depth, so holding specs is bounded memory)."""
    __slots__ = ("idx", "refs", "fetch", "fn", "args", "kwargs",
                 "dispatched_at", "trace_ctx")

    def __init__(self, idx, refs, fetch, fn, args, kwargs, dispatched_at):
        self.idx = idx
        self.refs = refs
        self.fetch = fetch
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.dispatched_at = dispatched_at
        # One trace per step: minted at first dispatch, reused for the
        # drain span and any replay re-dispatch so one step's dispatch,
        # worker execution, and drain assemble into one timeline.
        self.trace_ctx = None


def _pipeline_metrics():
    """Lazy metric handles (internal_kv needs a connected driver)."""
    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    return {
        "depth": Gauge("mesh_pipeline_depth",
                       "configured in-flight window of the step pipeline"),
        "inflight": Gauge("mesh_pipeline_inflight",
                          "steps currently in flight in the step pipeline"),
        "steps": Counter("mesh_pipeline_steps_total",
                         "pipeline steps drained"),
        "restarts": Counter("mesh_pipeline_replays_total",
                            "gang restarts absorbed by pipeline replay"),
        "dispatch": Histogram(
            "mesh_pipeline_dispatch_latency_s",
            "driver time to dispatch one step to every rank",
            boundaries=(0.0005, 0.002, 0.01, 0.05, 0.25, 1.0)),
        "drain": Histogram(
            "mesh_pipeline_drain_wait_s",
            "driver wait for the oldest in-flight step at backpressure",
            boundaries=(0.0005, 0.002, 0.01, 0.05, 0.25, 1.0, 5.0)),
    }


class StepPipeline:
    """Bounded-window asynchronous step stream over a MeshGroup.

    ``submit(fn, *args)`` dispatches ``fn(state, *args)`` to every rank
    and returns as soon as at most ``depth`` steps remain in flight — the
    workers always hold the next step(s) queued before the driver waits
    on any result, so driver RPC latency never serializes with device
    compute (zero per-step driver syncs; see driver_sync_count()).

    Results drain strictly in step order via the gang_get supervisor:
    rank death mid-window raises :class:`MeshGroupError` eagerly, and —
    when the group has restart budget — the gang is rebuilt,
    ``on_restart(group)`` re-materializes carry state, and the held
    in-flight window replays from the oldest undrained step.

    ``metrics_interval=N``: only every Nth step returns metrics (host-
    converted worker-side); others reply None.  ``on_result(idx, res)``
    fires for every drained step (res is None for non-fetch steps) — use
    it to checkpoint at drain cadence for exactly-once replay.

    Not thread-safe: one driver thread owns a pipeline.
    """

    def __init__(self, group: "MeshGroup", depth: int = 2,
                 metrics_interval: int = 1,
                 on_restart: Optional[Callable] = None,
                 on_result: Optional[Callable] = None,
                 drain_timeout: Optional[float] = None,
                 export_metrics: bool = True):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.group = group
        self.depth = depth
        self.metrics_interval = max(1, int(metrics_interval))
        self.on_restart = on_restart
        self.on_result = on_result
        self.drain_timeout = drain_timeout
        self._inflight: InflightWindow = InflightWindow(depth)
        self._results: List[Any] = []
        self._next_idx = 0
        self._drained = 0
        self.replay_count = 0
        self._closed = False
        self._broken = False
        # fn -> store ref cache: serialize each distinct step fn once, not
        # once per step (workers resolve the ref from their local cache).
        self._fn_refs: Dict[int, tuple] = {}
        self._metrics = None
        if export_metrics:
            try:
                self._metrics = _pipeline_metrics()
                self._metrics["depth"].set(float(depth))
            except Exception:
                self._metrics = None
        self._seek(0)

    # ---- internals ----
    def _seek(self, idx: int) -> None:
        """Arm every rank's sequence gate (setup/restart path — the only
        blocking fan-outs a pipeline ever does outside its drains)."""
        gang_get([w.pipeline_seek.remote(idx) for w in self.group.workers],
                 timeout=self.group.bootstrap_timeout)

    def _fn_ref(self, fn: Callable):
        cached = self._fn_refs.get(id(fn))
        if cached is not None and cached[0] is fn:
            return cached[1]
        ref = ray_tpu.put(fn)
        self._fn_refs[id(fn)] = (fn, ref)
        return ref

    def _dispatch(self, step: _InflightStep) -> None:
        t0 = time.perf_counter()
        from ray_tpu import observability as obs
        from ray_tpu._private import profiling

        minted = False
        if step.trace_ctx is None and obs.enabled():
            # Join the caller's trace when one is live (e.g. a learner
            # update_async boundary); mint a fresh per-step root else.
            step.trace_ctx = obs.get_context()
            if step.trace_ctx is None:
                step.trace_ctx = obs.mint_context()
                minted = True
        # Dispatch inside the step's trace so every rank's
        # pipeline_step submission (and its worker-side execution)
        # carries this step's trace id.
        saved = obs.set_context(step.trace_ctx) if step.trace_ctx else None
        try:
            fn_ref = self._fn_ref(step.fn)
            step.refs = [
                w.pipeline_step.remote(step.idx, step.fetch, fn_ref,
                                       *step.args, **step.kwargs)
                for w in self.group.workers
            ]
        finally:
            if step.trace_ctx:
                obs.set_context(saved)
        step.dispatched_at = time.perf_counter()
        # A freshly minted step records its dispatch AS the trace root so
        # the rank-side execute spans (parented to the root id) anchor a
        # real span — cross-process flow arrows need both ends.
        profiling.record_span("pipeline_dispatch", t0, step.dispatched_at,
                              step=step.idx, _trace_ctx=step.trace_ctx,
                              _root=minted)
        if self._metrics is not None and \
                step.idx % self.metrics_interval == 0:
            try:
                self._metrics["dispatch"].observe(step.dispatched_at - t0)
            except Exception:
                pass

    def _recover(self, cause: exc.MeshGroupError) -> None:
        """Gang restart + window replay.  Raises (budget exhausted /
        respawn failure) with the pipeline marked broken."""
        from ray_tpu import observability as obs

        obs.flight_record(f"gang_restart: {cause}")
        try:
            self.group._restart(cause)  # raises when out of budget
        except BaseException:
            self._broken = True
            raise
        if self.on_restart is not None:
            self.on_restart(self.group)
        base = self._inflight.peek().idx if self._inflight else self._next_idx
        self._seek(base)
        for step in self._inflight:
            self._dispatch(step)
        self.replay_count += 1
        if self._metrics is not None:
            try:
                self._metrics["restarts"].inc()
            except Exception:
                pass

    def _drain_one(self) -> None:
        step = self._inflight.peek()
        t0 = time.perf_counter()
        while True:
            try:
                res = gang_get(step.refs, timeout=self.drain_timeout)
                break
            except exc.MeshGroupError as e:
                self._recover(e)
                step = self._inflight.peek()
            except BaseException:
                self._broken = True
                raise
        t1 = time.perf_counter()
        from ray_tpu._private import profiling

        profiling.record_span("pipeline_drain", t0, t1, step=step.idx,
                              _trace_ctx=step.trace_ctx)
        self._inflight.popleft()
        self._drained += 1
        if step.fetch:
            self._results.append((step.idx, res))
        if self.on_result is not None:
            self.on_result(step.idx, res if step.fetch else None)
        if self._metrics is not None and \
                self._drained % self.metrics_interval == 0:
            try:
                self._metrics["steps"].inc(self.metrics_interval)
                self._metrics["inflight"].set(float(len(self._inflight)))
                self._metrics["drain"].observe(t1 - t0)
            except Exception:
                pass

    # ---- public API ----
    @property
    def inflight(self) -> int:
        return len(self._inflight)

    @property
    def steps_submitted(self) -> int:
        return self._next_idx

    @property
    def steps_drained(self) -> int:
        return self._drained

    def submit(self, fn: Callable, *args,
               fetch: Optional[bool] = None, **kwargs) -> int:
        """Dispatch one step to every rank; blocks (draining the oldest
        step) only once more than ``depth`` are in flight — so step N+1
        is always dispatched before step N-depth's result is awaited.
        Returns the step index."""
        if self._closed or self._broken:
            raise RuntimeError("pipeline is closed")
        idx = self._next_idx
        self._next_idx += 1
        if fetch is None:
            fetch = idx % self.metrics_interval == 0
        step = _InflightStep(idx, None, bool(fetch), fn, args, kwargs, 0.0)
        self._dispatch(step)
        self._inflight.append(step)
        while self._inflight.over_depth:
            self._drain_one()
        return idx

    def take_results(self) -> List[Any]:
        """Pop drained (idx, per-rank results) pairs accumulated so far —
        fetch steps only, in step order.  Non-blocking."""
        out, self._results = self._results, []
        return out

    def flush(self) -> List[Any]:
        """Drain every in-flight step, then return ALL fetched results
        accumulated since creation (non-destructive)."""
        while self._inflight:
            self._drain_one()
        return list(self._results)

    def close(self, flush: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if flush and not self._broken:
            while self._inflight:
                self._drain_one()
        else:
            _abandon([(s.idx, r) for s in self._inflight
                      for r in (s.refs or [])])
            self._inflight.clear()

    def __enter__(self) -> "StepPipeline":
        return self

    def __exit__(self, exc_type, exc_val, tb) -> None:
        # On an exception unwind, don't block on (possibly poisoned) work.
        self.close(flush=exc_type is None)


class MeshGroup:
    """A gang of one actor per TPU host forming one global jax mesh.

    ``MeshGroup(2, platform="cpu", local_device_count=2)`` on one machine
    builds a 4-device virtual mesh across 2 processes; on real hardware,
    ``MeshGroup(num_hosts, resources_per_host={"TPU": 4})`` gangs the pod.

    With ``max_group_restarts > 0`` the group self-heals: a rank death
    detected during ``run()`` tears the whole gang down (SPMD worlds die as
    a unit), re-spawns fresh worker processes, re-runs the rendezvous and
    retries the function — see the module docstring's *Fault tolerance*
    section.  ``restart_count`` and the ``mesh_group_restarts_total``
    metric record consumed budget.
    """

    def __init__(self, num_hosts: int,
                 resources_per_host: Optional[Dict[str, float]] = None,
                 platform: Optional[str] = None,
                 local_device_count: Optional[int] = None,
                 strategy: str = "PACK",
                 bootstrap_timeout: float = 120.0,
                 max_group_restarts: int = 0,
                 restart_backoff_s: float = 0.5,
                 restart_backoff_max_s: float = 30.0,
                 pipeline_depth: int = 2,
                 transport_restart_budget: int = 2):
        self.num_hosts = num_hosts
        self.platform = platform
        self.local_device_count = local_device_count
        self.strategy = strategy
        self.bootstrap_timeout = bootstrap_timeout
        self.max_group_restarts = max_group_restarts
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_max_s = restart_backoff_max_s
        self.restart_count = 0
        # Transport aborts (the gloo TCP race — see is_transport_abort)
        # rebuild under their own budget: they are environmental hiccups,
        # not workload failures, and must not consume the caller's
        # max_group_restarts headroom.
        self.transport_restart_budget = transport_restart_budget
        self.transport_restart_count = 0
        # Monotonic incarnation counter: every respawn (restart OR
        # resize) gets a fresh generation; equals restart_count when no
        # transport restarts/resizes occur, so generation-pinned chaos
        # schedules keep their meaning.
        self._generation = 0
        # Default StepPipeline window; also sizes the actor pool so up to
        # depth+1 queued pipeline steps can park on the sequence gate with
        # ping still answered on a free slot.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._resources = dict(resources_per_host or {"CPU": 1.0})
        self.pg = None
        self.workers: List[Any] = []
        # Group-level restart hooks: run inside _restart after every
        # successful respawn, BEFORE the caller's per-call on_restart —
        # for cross-cutting state that must react to any rebuild (e.g.
        # the checkpoint coordinator cancelling in-flight async commits
        # whose writers died with the old gang).
        self._restart_hooks: List[Callable] = []
        self._spawn(generation=0)

    # ---- gang lifecycle ----
    def _actor_opts(self) -> Dict[str, Any]:
        res = self._resources
        opts: Dict[str, Any] = {"max_concurrency": self.pipeline_depth + 2}
        if res.get("CPU"):
            opts["num_cpus"] = res["CPU"]
        if res.get("TPU"):
            opts["num_tpus"] = res["TPU"]
        extra = {k: v for k, v in res.items() if k not in ("CPU", "TPU")}
        if extra:
            opts["resources"] = extra
        return opts

    def _spawn(self, generation: int):
        """Reserve the placement group, spawn one fresh worker per host and
        run the jax.distributed rendezvous."""
        opts = self._actor_opts()
        if self.num_hosts > 1:
            from ray_tpu.util import PlacementGroupSchedulingStrategy
            from ray_tpu.util.placement_group import placement_group

            self.pg = placement_group(
                [dict(self._resources) for _ in range(self.num_hosts)],
                strategy=self.strategy)
            self.pg.ready(timeout=self.bootstrap_timeout)
            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                self.pg)
        # The rendezvous now includes a collective warm-up, so the gloo
        # connect race can surface right here — where a bounded in-place
        # retry (fresh actors, same placement group) is cheap and
        # invisible to the caller.
        attempts = 3
        for attempt in range(attempts):
            self.workers = [
                MeshWorker.options(**opts).remote(rank, self.num_hosts,
                                                  generation)
                for rank in range(self.num_hosts)
            ]
            try:
                self.device_info = rendezvous(self.workers, self.platform,
                                              self.local_device_count,
                                              timeout=self.bootstrap_timeout)
                return
            except exc.MeshGroupError as e:
                if attempt >= attempts - 1 or not is_transport_abort(e):
                    raise
                for w in self.workers:
                    try:
                        ray_tpu.kill(w)
                    except Exception:
                        pass
                self.workers = []
                time.sleep(0.2 * (attempt + 1))

    def _teardown_workers(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None

    def _restart(self, cause: exc.MeshGroupError) -> None:
        """One gang restart attempt: teardown + backoff + respawn.

        Raises ``MeshGroupError`` (the original cause, annotated with the
        consumed restart count) when the budget is exhausted; re-raises a
        respawn failure wrapped the same way."""
        restarts_total, restart_failures = None, None
        try:
            restarts_total, restart_failures = _restart_metrics()
        except Exception:
            pass  # metrics are best-effort (e.g. driver disconnecting)
        transport = is_transport_abort(cause)
        if transport:
            if self.transport_restart_count >= self.transport_restart_budget:
                cause.restarts = self.restart_count
                raise cause
            self.transport_restart_count += 1
        else:
            if self.restart_count >= self.max_group_restarts:
                cause.restarts = self.restart_count
                raise cause
            self.restart_count += 1
        attempt = self.restart_count + self.transport_restart_count
        backoff = min(
            self.restart_backoff_s * (2 ** (attempt - 1)),
            self.restart_backoff_max_s)
        self._teardown_workers()
        time.sleep(backoff)
        self._generation += 1
        try:
            self._spawn(generation=self._generation)
        except Exception as e:
            if restart_failures is not None:
                try:
                    restart_failures.inc()
                except Exception:
                    pass
            raise exc.MeshGroupError(
                f"gang restart {self.restart_count}/"
                f"{self.max_group_restarts} failed to respawn: {e}",
                failed_ranks=cause.failed_ranks,
                restarts=self.restart_count) from e
        if restarts_total is not None:
            try:
                restarts_total.inc()
            except Exception:
                pass
        for hook in self._restart_hooks:
            try:
                hook(self)
            except Exception:
                # Group-level hooks are advisory (cancellation, metrics);
                # state re-materialization belongs to per-call on_restart,
                # whose failures DO propagate.
                pass

    def add_restart_hook(self, hook: Callable[["MeshGroup"], None]) -> None:
        """Register ``hook(group)`` to run after every successful gang
        rebuild, before the per-call ``on_restart``.  Exceptions are
        swallowed — use for cross-cutting reactions (cancelling pending
        checkpoint commits, cache invalidation), not state rebuilds."""
        self._restart_hooks.append(hook)

    def resize(self, num_hosts: int) -> None:
        """Tear the gang down and rebuild it at ``num_hosts`` hosts.

        A ``jax.distributed`` world is fixed-size, so elasticity means a
        full rebuild: fresh worker processes, fresh placement group,
        fresh rendezvous, next generation.  The caller owns state — this
        carries nothing over (ElasticMeshGroup re-broadcasts its boundary
        snapshot afterwards)."""
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        self._teardown_workers()
        self.num_hosts = int(num_hosts)
        self._generation += 1
        self._spawn(generation=self._generation)

    # ---- health ----
    def health_check(self, deadline: float = 10.0) -> List[int]:
        """Ping every rank with a deadline.  Returns the rank list on
        success; raises ``MeshGroupError`` naming dead/unresponsive ranks.
        Safe to call while a ``run()`` is in flight (pings ride the spare
        concurrency slot)."""
        _note_driver_sync()
        futures = [w.ping.remote() for w in self.workers]
        return gang_get(futures, timeout=deadline)

    @property
    def global_device_count(self) -> int:
        return self.device_info[0]["global_devices"]

    # ---- execution ----
    def run(self, fn: Callable, *args, on_restart: Optional[Callable] = None,
            timeout: Optional[float] = None, **kwargs) -> List[Any]:
        """Fan fn out to every host process; returns per-rank results.

        Supervised: a rank death raises ``MeshGroupError`` eagerly; with
        ``max_group_restarts > 0`` the gang is rebuilt (fresh processes +
        rendezvous), ``on_restart(group)`` — if given — re-materializes
        host-pinned state, and fn is retried.  ``timeout`` is a per-attempt
        deadline for the whole fan-out."""
        _note_driver_sync()
        return self._supervised(
            lambda: gang_get([w.run.remote(fn, *args, **kwargs)
                              for w in self.workers], timeout=timeout),
            on_restart)

    def run_async(self, fn: Callable, *args, **kwargs):
        return [w.run.remote(fn, *args, **kwargs) for w in self.workers]

    def run_stateful(self, fn: Callable, *args,
                     on_restart: Optional[Callable] = None,
                     timeout: Optional[float] = None, **kwargs) -> List[Any]:
        _note_driver_sync()
        return self._supervised(
            lambda: gang_get([w.run_stateful.remote(fn, *args, **kwargs)
                              for w in self.workers], timeout=timeout),
            on_restart)

    # ---- pipelined execution (the zero-sync hot path) ----
    def pipeline(self, depth: Optional[int] = None,
                 metrics_interval: int = 1,
                 on_restart: Optional[Callable] = None,
                 on_result: Optional[Callable] = None,
                 drain_timeout: Optional[float] = None,
                 export_metrics: bool = True) -> StepPipeline:
        """Open a :class:`StepPipeline` over this gang (see its docs).
        ``depth`` defaults to the group's ``pipeline_depth``."""
        return StepPipeline(self, depth=depth or self.pipeline_depth,
                            metrics_interval=metrics_interval,
                            on_restart=on_restart, on_result=on_result,
                            drain_timeout=drain_timeout,
                            export_metrics=export_metrics)

    def run_pipelined(self, fn: Callable, num_steps: int, *args,
                      depth: Optional[int] = None,
                      metrics_interval: int = 1,
                      args_fn: Optional[Callable] = None,
                      on_restart: Optional[Callable] = None,
                      on_result: Optional[Callable] = None,
                      timeout: Optional[float] = None,
                      **kwargs) -> List[Any]:
        """Drive ``num_steps`` pipelined ``fn(state, *args)`` steps and
        return the fetched ``(step_idx, per-rank results)`` pairs (every
        ``metrics_interval``-th step).  ``args_fn(i)`` — when given —
        produces per-step positional args (e.g. a batch ref); otherwise
        every step receives ``*args``.  Supervision matches ``run()``:
        rank death restarts the gang under the restart budget and replays
        the in-flight window after ``on_restart``."""
        with self.pipeline(depth=depth, metrics_interval=metrics_interval,
                           on_restart=on_restart, on_result=on_result,
                           drain_timeout=timeout) as pipe:
            for i in range(num_steps):
                step_args = args_fn(i) if args_fn is not None else args
                pipe.submit(fn, *step_args, **kwargs)
            return pipe.flush()

    # ---- ordered per-rank dispatch (the MPMD stage-gang primitive) ----
    def seek_ranks(self, idx: int) -> None:
        """(Re)arm every rank's pipeline sequence gate at ``idx`` — the
        setup/restart fan-out for callers that drive the gang through
        :meth:`submit_ordered` instead of a :class:`StepPipeline`."""
        gang_get([w.pipeline_seek.remote(idx) for w in self.workers],
                 timeout=self.bootstrap_timeout)

    def submit_ordered(self, seq: int, calls: Sequence[tuple],
                       kwargs: Optional[dict] = None) -> List[Any]:
        """Dispatch one gated op per rank at sequence position ``seq``
        and return the per-rank refs WITHOUT draining.

        ``calls[r] = (fn, *args)`` runs ``fn(state, *args)`` on rank r
        through the MeshWorker pipeline gate: every rank executes its
        ops in the same global order, which is what keeps compiled
        cross-process collectives matched across ranks even though each
        op is an independent actor task.  The MPMD pipeline plane drives
        its multi-host stage gangs through this (one ``seq`` per
        schedule op); unlike ``run*`` it performs no blocking driver
        sync — callers drain the refs themselves (``gang_get``)."""
        if len(calls) != len(self.workers):
            raise ValueError(
                f"submit_ordered needs one call per rank "
                f"({len(self.workers)}), got {len(calls)}")
        kw = kwargs or {}
        return [
            w.pipeline_step.remote(seq, True, *calls[r], **kw)
            for r, w in enumerate(self.workers)
        ]

    def _supervised(self, attempt: Callable[[], List[Any]],
                    on_restart: Optional[Callable]) -> List[Any]:
        while True:
            try:
                return attempt()
            except exc.MeshGroupError as e:
                self._restart(e)  # raises when the budget is exhausted
                if on_restart is not None:
                    on_restart(self)

    def run_rank(self, rank: int, fn: Callable, *args, **kwargs):
        _note_driver_sync()
        return ray_tpu.get(self.workers[rank].run.remote(fn, *args, **kwargs))

    def run_rank_stateful(self, rank: int, fn: Callable, *args, **kwargs):
        _note_driver_sync()
        return ray_tpu.get(
            self.workers[rank].run_stateful.remote(fn, *args, **kwargs))

    def shutdown(self):
        self._teardown_workers()
