"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Absent from the reference entirely (SURVEY.md §5.7: no ring/ulysses/
sequence-parallel code exists there; it only provides the substrate —
placement groups + collective send/recv).  Here they are first-class:

- **Ring attention**: K/V shards rotate around the `sequence` mesh axis via
  `ppermute` (nearest-neighbour ICI hops on a TPU torus) while each device
  accumulates the flash-attention online-softmax recurrence for its local Q
  shard.  Peak memory per device is O(L/n · L/n) scores; no device ever
  holds the full sequence.  Autodiff flows through the scan+ppermute, so the
  backward pass is also a ring (reversed permutation), for free.
- **Ulysses**: all_to_all swaps the sharded axis from sequence to heads,
  computes exact local attention, and swaps back — cheaper at moderate L
  when heads ≥ mesh axis size.

Both run under shard_map over a named mesh axis.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import blockwise_update, finalize_blockwise


def _ring_fwd(q, k, v, *, axis_name: str, axis_size: int, causal: bool,
              sm_scale: Optional[float]):
    """Per-device body (inside shard_map). q,k,v: [B, Lloc, H, D]."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    o = jnp.zeros((b, lq, h, d), jnp.float32)
    l = jnp.zeros((b, h, lq), jnp.float32)
    m = jnp.full((b, h, lq), -1e30, jnp.float32)

    def step(carry, t):
        o, l, m, k_cur, v_cur = carry
        src_idx = (my_idx - t) % axis_size  # whose K/V block we now hold
        if causal:
            # Global positions decide the mask: full block, masked block, or
            # the diagonal block with a triangular mask.
            q_pos = my_idx * lq + jnp.arange(lq)
            k_pos = src_idx * lk + jnp.arange(lk)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        o, l, m = blockwise_update(q, k_cur, v_cur, o, l, m, mask,
                                   sm_scale=sm_scale)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, l, m, k_nxt, v_nxt), None

    (o, l, m, _, _), _ = jax.lax.scan(step, (o, l, m, k, v),
                                      jnp.arange(axis_size))
    return finalize_blockwise(o, l).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis: str = "sequence",
                   causal: bool = True, sm_scale: Optional[float] = None,
                   batch_axes=("data", "fsdp")):
    """Ring attention over global arrays [B, L, H, D] sharded on L.

    Usable standalone or composed inside a larger pjit program; the shard_map
    boundary keeps the ppermute schedule explicit while XLA still fuses the
    local blockwise math."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names:
        # Degenerate mesh (e.g. single chip): plain attention.
        from ray_tpu.ops.attention import mha_attention

        return mha_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    axis_size = mesh.shape[axis]
    bax = tuple(a for a in batch_axes if a in mesh.axis_names)
    spec = P(bax if bax else None, axis, None, None)
    fn = functools.partial(_ring_fwd, axis_name=axis, axis_size=axis_size,
                           causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)


def _ulysses_fwd(q, k, v, *, axis_name: str, axis_size: int, causal: bool,
                 sm_scale: Optional[float]):
    from ray_tpu.ops.attention import mha_attention

    # [B, L/n, H, D] → all_to_all → [B, L, H/n, D]
    def swap_in(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def swap_out(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = swap_in(q), swap_in(k), swap_in(v)
    out = mha_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale,
                        use_flash=False)
    return swap_out(out)


def ulysses_attention(q, k, v, mesh, axis: str = "sequence",
                      causal: bool = True, sm_scale: Optional[float] = None,
                      batch_axes=("data", "fsdp")):
    """Ulysses-style sequence parallelism: all_to_all head/sequence swap.

    Requires num_heads % axis_size == 0."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names:
        from ray_tpu.ops.attention import mha_attention

        return mha_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    axis_size = mesh.shape[axis]
    if q.shape[2] % axis_size:
        raise ValueError(
            f"num_heads {q.shape[2]} not divisible by axis size {axis_size}")
    bax = tuple(a for a in batch_axes if a in mesh.axis_names)
    spec = P(bax if bax else None, axis, None, None)
    fn = functools.partial(_ulysses_fwd, axis_name=axis, axis_size=axis_size,
                           causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)
