"""Pipeline parallelism: GPipe-style microbatch schedule inside one SPMD
program (shard_map over a `pipe` mesh axis, stage hand-off via ppermute).

The reference has no pipeline parallelism (SURVEY.md §2.4) — only the
substrate (placement groups + collective send/recv between actors).  The
TPU-native design runs the whole pipeline *inside one compiled program*:
every device holds one stage's weights, activations rotate along the ring,
and XLA overlaps the ppermute with the next microbatch's compute.  Autodiff
through the scan+ppermute yields the reversed-ring backward schedule
automatically.  MPMD pipelines across *meshes* (per PAPERS.md's MPMD
pipeline paper) layer on top via the actor runtime; this module is the
intra-mesh SPMD form.

Constraint: all stages share one activation shape [mb, ...] (uniform-stack
transformer assumption).
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack per-stage pytrees along a new leading 'stage' axis (shard it
    over the pipe mesh axis with logical axis name "stage")."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _pipeline_body(stacked_params, x_micro, *, stage_fn, axis_name, n_stages,
                   n_micro, remat):
    """Inside shard_map. stacked_params leaves: [1, ...] (this device's
    stage); x_micro: [n_micro, mb, ...] (replicated along pipe)."""
    params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    total_steps = n_micro + n_stages - 1

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    mb_shape = x_micro.shape[1:]
    state = jnp.zeros(mb_shape, x_micro.dtype)
    outputs = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)

    def step(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (clamped; masked when t >= n_micro).
        inject = x_micro[jnp.minimum(t, n_micro - 1)]
        state = jnp.where(idx == 0, inject, state)
        out = fn(params, state)
        # Last stage records finished microbatch (t - (n_stages-1)).
        widx = t - (n_stages - 1)
        valid = jnp.logical_and(idx == n_stages - 1, widx >= 0)
        upd = jax.lax.dynamic_update_slice(
            outputs, out[None].astype(outputs.dtype),
            (jnp.maximum(widx, 0),) + (0,) * len(mb_shape))
        outputs = jnp.where(valid, upd, outputs)
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    (state, outputs), _ = jax.lax.scan(step, (state, outputs),
                                       jnp.arange(total_steps))
    # Only the last stage holds real outputs; broadcast them along the ring
    # so the result is replicated over `pipe`.
    mask = (idx == n_stages - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * mask, axis_name)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params: Any, x_micro: jax.Array, mesh,
                   axis: str = "pipe", remat: bool = True) -> jax.Array:
    """Run `stage_fn` as an n-stage pipeline over the mesh's `pipe` axis.

    stage_fn(params_i, x: [mb, ...]) -> [mb, ...]
    stacked_params: pytree with leading stage axis == mesh.shape[axis]
    x_micro: [n_micro, mb, ...] microbatched input
    Returns [n_micro, mb, ...] outputs (replicated over `pipe`).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names:
        # No pipe axis: run stages sequentially (single-device fallback).
        n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

        def seq(x):
            for i in range(n_stages):
                p_i = jax.tree_util.tree_map(lambda p: p[i], stacked_params)
                x = stage_fn(p_i, x)
            return x

        return jax.vmap(seq)(x_micro)

    n_stages = mesh.shape[axis]
    n_micro = x_micro.shape[0]
    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    body = functools.partial(_pipeline_body, stage_fn=stage_fn,
                             axis_name=axis, n_stages=n_stages,
                             n_micro=n_micro, remat=remat)
    return shard_map(body, mesh=mesh,
                     in_specs=(param_spec, P()), out_specs=P(),
                     check_rep=False)(stacked_params, x_micro)


def microbatch(x: jax.Array, n_micro: int) -> jax.Array:
    """[B, ...] → [n_micro, B/n_micro, ...]"""
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by {n_micro}")
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
