"""Sharding rules: logical array axes → mesh axes.

The pjit idiom: parameters and activations carry *logical* axis names
("batch", "embed", "mlp", "heads", "seq", ...), and a rule table maps those
to mesh axes.  This replaces the reference's per-framework wrapping (DDP
module wrap, tower splits): instead of wrapping modules, we annotate shapes
and let XLA insert the collectives.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

# Default logical→mesh rules for transformer-family models.
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("data", "fsdp")),
    ("seq", "sequence"),
    ("embed", None),
    ("embed_fsdp", "fsdp"),
    ("heads", "model"),
    ("kv", None),
    ("mlp", "model"),
    ("vocab", "model"),
    ("expert", "expert"),
    ("stage", "pipe"),
)


class ShardingRules:
    def __init__(self, rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES):
        self.table: Dict[str, Any] = dict(rules)

    def spec_for(self, logical_axes: Sequence[Optional[str]], mesh) -> "object":
        """PartitionSpec for an array annotated with logical axis names.
        Mesh axes not present in `mesh` degrade to replication, so the same
        model code runs on 1 chip and on a pod."""
        from jax.sharding import PartitionSpec as P

        entries = []
        for ax in logical_axes:
            if ax is None:
                entries.append(None)
                continue
            mapped = self.table.get(ax)
            if mapped is None:
                entries.append(None)
            elif isinstance(mapped, tuple):
                present = tuple(m for m in mapped if m in mesh.axis_names)
                entries.append(present if present else None)
            else:
                entries.append(mapped if mapped in mesh.axis_names else None)
        return P(*entries)

    def sharding_for(self, logical_axes: Sequence[Optional[str]], mesh):
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.spec_for(logical_axes, mesh))


def batch_sharding(mesh, ndim: int = 2):
    """Shard dim-0 over the data axes; replicate the rest."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    return NamedSharding(mesh, P(data_axes if data_axes else None,
                                 *([None] * (ndim - 1))))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def shard_params(params, mesh, rules: Optional[ShardingRules] = None,
                 annotations=None):
    """Place a parameter pytree on the mesh.

    `annotations` is a matching pytree of logical-axis tuples (or None for
    replicated).  Without annotations, everything is replicated — correct,
    just not memory-scaled (pure DP)."""
    import jax

    rules = rules or ShardingRules()

    if annotations is None:
        sharding = replicated(mesh)
        return jax.device_put(params, sharding)

    def place(leaf, ann):
        s = (rules.sharding_for(ann, mesh) if ann is not None
             else replicated(mesh))
        return jax.device_put(leaf, s)

    return jax.tree_util.tree_map(place, params, annotations,
                                  is_leaf=lambda x: x is None)


def constraint(x, logical_axes, mesh, rules: Optional[ShardingRules] = None):
    """with_sharding_constraint by logical axis names (inside jit)."""
    import jax

    rules = rules or ShardingRules()
    return jax.lax.with_sharding_constraint(
        x, rules.sharding_for(logical_axes, mesh))
