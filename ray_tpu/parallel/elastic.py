"""Elastic data parallelism: grow/shrink a live ZeRO DP gang mid-run.

Every piece of the elasticity story exists in isolation in this repo —
N→M resharded opt-state restore (``zero.reshard_opt_state`` +
``place_opt_state``), budgeted gang restarts (MeshGroup), node-death
detection, and an autoscaler that already scales serve replicas.  This
module composes them into a *training* plane whose world size can change
between steps without losing one:

- :class:`ElasticMeshGroup` drives a MeshGroup-hosted DP run whose host
  count floats inside ``num_hosts=(min, max)``.  A **grow** (autoscaler
  offers capacity) and a **notice shrink** (``preemption_notice``) both
  land at a step boundary: the gang snapshots, is rebuilt at the new
  size, receives ONE versioned ``ray_tpu.put`` weight broadcast, and the
  ZeRO optimizer shards re-partition N→M through the assembled
  ``(total,)`` form — no disk round trip.  A **lease expiry** (SIGKILL,
  no notice) surfaces as a MeshGroupError; the survivors' size is fitted,
  the gang rebuilds from the last boundary snapshot, and any steps since
  are *replayed* deterministically — ``steps_lost == 0`` by construction.

- The step itself (:func:`build_elastic_step`) is **slot-deterministic**:
  the global batch is a fixed number of ``slots`` microbatches regardless
  of world size, each slot's gradient is computed by an identical
  per-slot program, and the combine is an all_gather into global slot
  order followed by a fixed-length ordered sum.  Every rank computes the
  identical full gradient; only the optimizer chunk it *applies* depends
  on its rank.  All cross-rank collectives are pure data movement, so the
  parameter trajectory is **bitwise identical for any world size that
  divides ``slots``** — which is what lets a chaos test assert that a
  gang SIGKILLed at lease expiry finishes bitwise-equal to an unkilled
  run at the surviving size (the in-process
  :func:`reference_trajectory` IS that run).

Note ``zero.zero_clip_by_global_norm`` reconstructs the norm with a psum
whose operand layout depends on the world size; elastic steps that clip
use the ``grad_clip`` argument here instead (a fixed-length norm over the
unpadded gradient), which is world-invariant.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import chaos
from ray_tpu.parallel import mesh_group as mg
from ray_tpu.parallel import zero
from ray_tpu.parallel.zero import DATA_AXIS

logger = logging.getLogger(__name__)


# ---- the slot-deterministic step ----
def build_elastic_step(loss_fn: Callable, tx, sharder: "zero.ZeroSharder",
                       *, slots: int, world: Optional[int] = None,
                       axis: str = DATA_AXIS,
                       grad_clip: Optional[float] = None) -> Callable:
    """ZeRO DP step for use inside a shard_map body whose parameter
    trajectory is bitwise-invariant to the mesh size.

    The local batch is ``slots/world`` microbatch slots; each slot runs an
    identical ``value_and_grad`` + flatten program (``jax.lax.map``, so
    the per-slot HLO does not depend on the local count), the per-slot
    flat gradients are all_gathered into GLOBAL slot order (rank-major ==
    slot order because the batch is placed ``P(axis)`` on its leading
    dim), and the mean is one fixed-length ordered sum over ``slots``
    computed identically on every rank.  The optimizer update then runs
    per LANE at a fixed lane width: ``sharder`` is built at lane
    granularity (``sharder.world`` lanes — the same count at every gang
    size) and each rank ``lax.map``s ``tx.update`` over the lanes it
    owns.  An elementwise update compiled at a world-dependent chunk
    shape picks up shape-dependent codegen (fusion/vector width) and can
    drift by 1 ulp; per-lane mapping keeps the compiled update program —
    like the per-slot grad program — independent of ``world``.
    ``grad_clip`` applies a world-invariant global-norm clip over the
    unpadded gradient."""
    import jax
    import jax.numpy as jnp
    import optax

    world = sharder.world if world is None else int(world)
    lanes = sharder.world
    if slots % world:
        raise ValueError(f"slots={slots} not divisible by world={world}")
    if lanes % world:
        raise ValueError(
            f"lane count {lanes} not divisible by world={world}")

    def step(params, opt_block, batch):
        def slot_grad(mb):
            loss, g = jax.value_and_grad(loss_fn)(params, mb)
            flat, repl = sharder.split(g)
            return loss, flat, repl

        losses, flats, repls = jax.lax.map(slot_grad, batch)
        if world > 1:
            losses = jax.lax.all_gather(losses, axis).reshape(slots)
            flats = jax.lax.all_gather(flats, axis).reshape(
                slots, sharder.padded)
            repls = tuple(
                jax.lax.all_gather(r, axis).reshape((slots,) + r.shape[1:])
                for r in repls)
        # Ordered chain of binary adds over the slot axis, NOT jnp.sum:
        # XLA's reduce may lower with a layout-dependent association
        # (local-partial-then-combine after an all_gather), which breaks
        # the bitwise world-invariance contract.  A static chain of adds
        # in global slot order is associated identically everywhere.
        def slot_sum(stacked):
            acc = stacked[0]
            for s in range(1, slots):
                acc = acc + stacked[s]
            return acc

        loss = slot_sum(losses) / np.float32(slots)
        g_full = slot_sum(flats) / np.float32(slots)
        g_repl = tuple(slot_sum(r) / np.float32(slots) for r in repls)
        if grad_clip is not None:
            sq = jnp.sum(jnp.square(
                g_full[: sharder.total].astype(jnp.float32)))
            for r in g_repl:
                sq = sq + jnp.sum(jnp.square(r.astype(jnp.float32)))
            norm = jnp.sqrt(sq)
            scale = jnp.where(norm < np.float32(grad_clip),
                              jnp.float32(1.0), np.float32(grad_clip) / norm)
            g_full = (g_full.astype(jnp.float32) * scale).astype(g_full.dtype)
            g_repl = tuple((r.astype(jnp.float32) * scale).astype(r.dtype)
                           for r in g_repl)
        k = lanes // world
        idx = jax.lax.axis_index(axis) if world > 1 else 0
        g_rows = jax.lax.dynamic_slice_in_dim(
            sharder.rows(g_full.astype(sharder.dtype)), idx * k, k, 0)
        p_flat, p_repl = sharder.split(params)
        p_rows = jax.lax.dynamic_slice_in_dim(
            sharder.rows(p_flat), idx * k, k, 0)
        # Lane-replicated view of the opt state: shard leaves arrive as
        # this rank's [k, lane] block; everything else (counts, state for
        # replicated leaves) is broadcast so lax.map can carry it.
        opt_lanes = jax.tree_util.tree_map_with_path(
            lambda kp, x: x if (zero._is_shard_path(kp)
                                and getattr(x, "ndim", 0) >= 2)
            else jnp.broadcast_to(x, (k,) + jnp.shape(x)), opt_block)

        def lane_update(lane):
            g_l, p_l, o_l = lane
            c_grads = {"shard": g_l, "repl": g_repl}
            c_params = {"shard": p_l, "repl": p_repl}
            updates, o_out = tx.update(c_grads, o_l, c_params)
            return optax.apply_updates(c_params, updates), o_out

        new_c, opt_stack = jax.lax.map(lane_update,
                                       (g_rows, p_rows, opt_lanes))
        # Un-stack what lax.map replicated: per-lane shard state keeps
        # its [k, lane] block shape; everything else was advanced
        # identically in every lane, so lane 0's copy is THE copy.
        opt_out = jax.tree_util.tree_map_with_path(
            lambda kp, x: x if (zero._is_shard_path(kp)
                                and getattr(x, "ndim", 0) >= 2) else x[0],
            opt_stack)
        new_repl = tuple(r[0] for r in new_c["repl"])
        if world > 1:
            new_rows = jax.lax.all_gather(new_c["shard"], axis, tiled=True)
        else:
            new_rows = new_c["shard"]
        return (sharder.merge(new_rows.reshape(sharder.padded), new_repl),
                opt_out, loss)

    return step


# ---- placement / assembly helpers (host <-> mesh) ----
def _place_tree(tree: Any, mesh, spec, multihost: bool) -> Any:
    """Place a host pytree on ``mesh`` with one PartitionSpec for every
    leaf (``P()`` replicated, ``P(DATA_AXIS)`` leading-dim sharded).
    ``multihost`` routes through ``make_array_from_callback`` so each
    process materializes only its addressable shards."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, spec)

    def place(x):
        arr = np.asarray(x)
        if not multihost:
            return jax.device_put(jnp.asarray(arr), sh)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx, _a=arr: _a[idx])

    return jax.tree_util.tree_map(place, tree)


def _assemble_opt(host_opt: Any, total: int) -> Any:
    """Collapse a replicated-layout host opt state into the world-agnostic
    *assembled* form: shard leaves become unpadded ``(total,)`` vectors
    (what ``ZeroSharder.reshard_opt_state`` re-chunks onto any world)."""
    import jax

    def pick(kp, x):
        a = np.asarray(x)
        if zero._is_shard_path(kp) and a.ndim >= 2:
            return a.reshape(-1)[:total]
        return a

    return jax.tree_util.tree_map_with_path(pick, host_opt)


def _build_engine(spec: Dict[str, Any], params_host: Any, mesh,
                  multihost: bool) -> Dict[str, Any]:
    """The per-incarnation compiled machinery — shared verbatim by the
    gang workers and the in-process LocalElastic reference so both run
    the identical program."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.rllib.utils.mesh import _shard_map

    world = int(np.prod(list(mesh.shape.values())))
    tx = spec["tx_factory"]()
    # Lane-granularity sharder: a FIXED lane count regardless of gang
    # size, so the opt layout ([lanes, lane] leaves, each rank owning
    # lanes/world of them) and the compiled per-lane update are identical
    # at every world — the bitwise-invariance contract.  2x slots keeps
    # every rank at >= 2 lanes even at the largest world (= slots): a
    # trip-count-1 lax.map is inlined by XLA's while-loop simplifier and
    # the re-fused body compiles differently from the looped one.
    sharder = zero.ZeroSharder(params_host, 2 * spec["slots"],
                               should_shard=spec.get("should_shard"))
    opt_specs = sharder.opt_specs(tx)
    step = build_elastic_step(spec["loss_fn"], tx, sharder,
                              slots=spec["slots"], world=world,
                              grad_clip=spec.get("grad_clip"))
    stepj = jax.jit(_shard_map(step, mesh=mesh,
                               in_specs=(P(), opt_specs, P(DATA_AXIS)),
                               out_specs=(P(), opt_specs, P())))
    return {"tx": tx, "sharder": sharder, "opt_specs": opt_specs,
            "stepj": stepj, "world": world}


def _restore_state(spec, params_host, opt_assembled, mesh, multihost):
    """(params_dev, opt_dev, engine): place a snapshot (or fresh init when
    ``opt_assembled`` is None) onto ``mesh`` under the ZeRO layout."""
    import jax
    from jax.sharding import PartitionSpec as P

    engine = _build_engine(spec, params_host, mesh, multihost)
    sharder, tx = engine["sharder"], engine["tx"]
    params = _place_tree(params_host, mesh, P(), multihost)
    if opt_assembled is None:
        host_opt = jax.device_get(sharder.init_opt_state(tx, params_host))
    else:
        host_opt = jax.device_get(sharder.reshard_opt_state(opt_assembled))
    opt = zero.place_opt_state(host_opt, mesh, engine["opt_specs"],
                               multihost=multihost)
    return params, opt, engine


# ---- worker-side functions (module-level: pickled by reference) ----
def _elastic_setup(state, spec, params_host, opt_assembled, step0, version):
    """Build/rebuild a rank's elastic engine from the driver snapshot.
    Runs on every rank via ``run_stateful``; ``params_host`` and
    ``opt_assembled`` arrive as ONE ``ray_tpu.put`` ref each (the
    versioned one-put broadcast — the object store fans out, not the
    driver)."""
    import jax
    from jax.sharding import Mesh

    multihost = jax.process_count() > 1
    mesh = Mesh(np.asarray(jax.devices()), (DATA_AXIS,))
    params, opt, engine = _restore_state(spec, params_host, opt_assembled,
                                         mesh, multihost)
    state.clear()
    state.update(engine)
    state.update(
        rank=jax.process_index(), mesh=mesh, multihost=multihost,
        spec=spec, params=params, opt=opt, step=int(step0),
        version=int(version))
    return {"rank": state["rank"], "world": engine["world"],
            "step": int(step0), "version": int(version)}


def _elastic_step_fn(state, step_idx):
    """One global step at index ``step_idx`` (the driver replays indices
    after a recovery; ``batch_fn(step_idx)`` makes replay deterministic).
    The ``elastic_step`` chaos op fires HERE — a SIGKILL at this point is
    the no-notice lease-expiry drill."""
    import jax
    from jax.sharding import PartitionSpec as P

    chaos.maybe_die("elastic_step", state["rank"])
    batch = state["spec"]["batch_fn"](int(step_idx))
    batch_dev = _place_tree(batch, state["mesh"], P(DATA_AXIS),
                            state["multihost"])
    params, opt, loss = state["stepj"](state["params"], state["opt"],
                                       batch_dev)
    state["params"], state["opt"] = params, opt
    state["step"] = int(step_idx) + 1
    return float(jax.device_get(loss))


def _elastic_snapshot_fn(state):
    """Boundary snapshot: replicate the sharded opt state (a collective —
    EVERY rank participates, which is how survivors obtain a doomed
    rank's chunk over the transfer plane), then rank 0 assembles the
    world-agnostic form and returns it with the params."""
    import jax

    repl_opt = zero.replicate_opt_state(state["opt"], state["mesh"])
    if state["rank"] != 0:
        return None
    host_opt = jax.device_get(repl_opt)
    return {"step": state["step"],
            "params": jax.device_get(state["params"]),
            "opt": _assemble_opt(host_opt, state["sharder"].total)}


def _elastic_params_host(state):
    import jax

    return jax.device_get(state["params"])


# ---- in-process reference runner ----
class LocalElastic:
    """The elastic engine on in-process virtual devices — the *reference
    implementation* the gang is bitwise-compared against.  ``resize``
    runs the exact snapshot→assemble→reshard→place protocol the gang
    uses, just without actors."""

    def __init__(self, loss_fn: Callable, params_factory: Callable,
                 tx_factory: Callable, batch_fn: Callable, *,
                 slots: int = 4, world: int = 1,
                 grad_clip: Optional[float] = None,
                 should_shard: Optional[Callable] = None):
        self.spec = {"loss_fn": loss_fn, "tx_factory": tx_factory,
                     "batch_fn": batch_fn, "slots": slots,
                     "grad_clip": grad_clip, "should_shard": should_shard}
        self._params_host = params_factory()
        self.step_idx = 0
        self.losses: List[float] = []
        self._mount(world, opt_assembled=None)

    def _mount(self, world: int, opt_assembled):
        import jax
        from jax.sharding import Mesh

        devs = jax.devices()
        if world > len(devs):
            raise ValueError(f"world={world} > {len(devs)} local devices")
        self.mesh = Mesh(np.asarray(devs[:world]), (DATA_AXIS,))
        self.params, self.opt, engine = _restore_state(
            self.spec, self._params_host, opt_assembled, self.mesh,
            multihost=False)
        self.sharder = engine["sharder"]
        self._stepj = engine["stepj"]
        self.world = world

    def step(self) -> float:
        import jax
        from jax.sharding import PartitionSpec as P

        batch = self.spec["batch_fn"](self.step_idx)
        batch_dev = _place_tree(batch, self.mesh, P(DATA_AXIS), False)
        self.params, self.opt, loss = self._stepj(self.params, self.opt,
                                                  batch_dev)
        self.step_idx += 1
        loss = float(jax.device_get(loss))
        self.losses.append(loss)
        return loss

    def resize(self, world: int):
        """Snapshot → assembled opt form → remount at ``world``."""
        import jax

        if world == self.world:
            return
        host_opt = jax.device_get(
            zero.replicate_opt_state(self.opt, self.mesh))
        assembled = _assemble_opt(host_opt, self.sharder.total)
        self._params_host = jax.device_get(self.params)
        self._mount(world, opt_assembled=assembled)

    def params_host(self) -> Any:
        import jax

        return jax.device_get(self.params)


def reference_trajectory(loss_fn: Callable, params_factory: Callable,
                         tx_factory: Callable, batch_fn: Callable, *,
                         steps: int, slots: int = 4, world: int = 1,
                         grad_clip: Optional[float] = None,
                         resize_plan: Optional[Dict[int, int]] = None
                         ) -> Dict[str, Any]:
    """Run ``steps`` elastic steps in-process and return ``{"params",
    "losses"}``.  ``resize_plan={step: new_world}`` reshards mid-run at
    the given step boundaries — by slot-determinism the final params are
    bitwise-independent of the plan (the property the elastic tests pin
    down)."""
    le = LocalElastic(loss_fn, params_factory, tx_factory, batch_fn,
                      slots=slots, world=world, grad_clip=grad_clip)
    for s in range(steps):
        if resize_plan and s in resize_plan:
            le.resize(resize_plan[s])
        le.step()
    return {"params": le.params_host(),
            "losses": np.asarray(le.losses, dtype=np.float64)}


# ---- the driver-side elastic gang ----
class ElasticMeshGroup:
    """A data-parallel training gang whose host count floats inside
    ``num_hosts=(min, max)`` without ever losing a step.

    Resizes are full gang rebuilds at a step boundary (a jax.distributed
    world is fixed-size): the driver keeps a boundary snapshot
    ``{step, params, assembled opt}``, broadcasts it as one versioned
    ``ray_tpu.put`` per tree, and the new gang re-chunks the opt state
    onto its world via the ``reshard_opt_state``/``place_opt_state``
    path.  Grows and notice-shrinks snapshot first (graceful — the
    doomed rank still participates in the snapshot collective); a lease
    expiry (rank SIGKILLed with no notice) is caught as a
    MeshGroupError, the surviving count is fitted to an allowed size,
    and the missed steps are replayed deterministically from
    ``batch_fn`` — ``elastic_steps_lost_total`` stays 0 by construction.
    Transport aborts (the gloo TCP race) rebuild at the SAME size under
    their own budget and are not counted as shrinks."""

    def __init__(self, loss_fn: Callable, params_factory: Callable,
                 tx_factory: Callable, batch_fn: Callable, *,
                 num_hosts: Tuple[int, int] = (1, 2),
                 initial_hosts: Optional[int] = None,
                 platform: Optional[str] = None,
                 local_device_count: Optional[int] = None,
                 slots: int = 4, grad_clip: Optional[float] = None,
                 should_shard: Optional[Callable] = None,
                 snapshot_interval: int = 1,
                 resources_per_host: Optional[Dict[str, float]] = None,
                 bootstrap_timeout: float = 120.0,
                 transport_restart_budget: int = 2):
        if isinstance(num_hosts, int):
            num_hosts = (num_hosts, num_hosts)
        lo, hi = int(num_hosts[0]), int(num_hosts[1])
        if not (1 <= lo <= hi):
            raise ValueError(f"bad num_hosts range {num_hosts}")
        ldc = int(local_device_count or 1)
        self.allowed_hosts = [h for h in range(lo, hi + 1)
                              if slots % (h * ldc) == 0]
        if not self.allowed_hosts:
            raise ValueError(
                f"no host count in [{lo}, {hi}] divides slots={slots} "
                f"with local_device_count={ldc}")
        self.min_hosts, self.max_hosts = lo, hi
        self.slots = slots
        self.snapshot_interval = max(1, int(snapshot_interval))
        self.transport_restart_budget = int(transport_restart_budget)
        self._mg_kwargs = dict(platform=platform,
                               local_device_count=local_device_count,
                               resources_per_host=resources_per_host,
                               bootstrap_timeout=bootstrap_timeout,
                               max_group_restarts=0)
        self.spec = {"loss_fn": loss_fn, "tx_factory": tx_factory,
                     "batch_fn": batch_fn, "slots": slots,
                     "grad_clip": grad_clip, "should_shard": should_shard}
        self._step = 0          # global steps completed
        self._gang_step = 0     # next index the live gang will execute
        self._gang_calls = 0    # elastic_step invocations this incarnation
        self._version = 0
        self._snapshot = {"step": 0, "params": params_factory(),
                          "opt": None}
        self._pending_resize: Optional[int] = None
        self._notices: List[Tuple[int, float]] = []
        self._pending_steps = 0
        self.counters: Dict[str, float] = {
            "elastic_grows_total": 0, "elastic_shrinks_total": 0,
            "elastic_notice_shrinks_total": 0,
            "elastic_expiry_shrinks_total": 0,
            "elastic_transport_rebuilds_total": 0,
            "elastic_reshard_seconds_total": 0.0,
            "elastic_replayed_steps_total": 0,
            "elastic_steps_lost_total": 0,
            "elastic_weight_puts_total": 0,
        }
        self.hosts = self._fit(initial_hosts if initial_hosts is not None
                               else self.allowed_hosts[-1])
        self.group = mg.MeshGroup(num_hosts=self.hosts, **self._mg_kwargs)
        self._setup_gang()

    # ---- sizing ----
    def _fit(self, target: int) -> int:
        """Largest allowed host count <= target (floor: the smallest
        allowed size — a gang never dissolves below min)."""
        ok = [h for h in self.allowed_hosts if h <= target]
        return ok[-1] if ok else self.allowed_hosts[0]

    # ---- gang (re)build ----
    def _setup_gang(self):
        snap = self._snapshot
        self._version += 1
        # One put per rebuild; the N gang ranks resolve these refs
        # concurrently, which the transfer plane turns into a striped
        # cooperative broadcast (receivers serve each other's landed
        # ranges) — rebuild cost stays ~O(snapshot/BW) as the gang grows.
        params_ref = ray_tpu.put(snap["params"])
        opt_ref = ray_tpu.put(snap["opt"]) if snap["opt"] is not None \
            else None
        self.counters["elastic_weight_puts_total"] += 1
        self.group.run_stateful(_elastic_setup, self.spec, params_ref,
                                opt_ref, snap["step"], self._version)
        self._gang_step = snap["step"]
        self._gang_calls = 0

    def _resize_to(self, n: int):
        t0 = time.monotonic()
        self.group.resize(n)
        self.hosts = n
        self._setup_gang()
        self.counters["elastic_reshard_seconds_total"] += \
            time.monotonic() - t0
        self._export_metrics()

    def _refresh_snapshot(self, force: bool = False):
        if not force and self._step % self.snapshot_interval:
            return
        out = self.group.run_stateful(_elastic_snapshot_fn)
        snap = next(s for s in out if s is not None)
        self._snapshot = snap

    # ---- elasticity signals ----
    def request_resize(self, target: int):
        """Ask for a new size; applied at the next step boundary."""
        self._pending_resize = self._fit(int(target))

    def offer_capacity(self, spare_hosts: int):
        """Autoscaler hook: grow into ``spare_hosts`` extra hosts."""
        if spare_hosts > 0:
            self.request_resize(self.hosts + int(spare_hosts))

    def preemption_notice(self, rank: int, deadline_s: float = 30.0):
        """A host will disappear in ``deadline_s``: shrink gracefully at
        the next step boundary (the doomed rank still participates in
        the boundary snapshot — survivors get its opt chunk for free)."""
        self._notices.append((int(rank), time.monotonic() + deadline_s))

    def arm_lease_expiry(self, rank: int, after_steps: int):
        """The no-notice drill: schedule a SIGKILL of ``rank`` at the
        ``after_steps``-th future elastic step via the chaos plane (spot
        reclaim with zero warning — recovery must come from the
        snapshot + replay path, not a goodbye collective)."""
        # Chaos invocation counts start from zero when a schedule is
        # (re)armed, so nth counts elastic steps from NOW.
        spec = f"elastic_step:{rank}:{int(after_steps)}:*"
        ray_tpu.get(self.group.workers[rank].setup_env.remote(
            {chaos.KILL_SCHEDULE_ENV: spec}))

    def pending_steps(self) -> int:
        """Steps queued behind the gang (the autoscaler gang policy's
        scale signal)."""
        return self._pending_steps

    # ---- the step loop ----
    def step(self) -> float:
        """Advance the run by exactly one global step, absorbing any
        pending resize (boundary) and any gang failure (recovery +
        deterministic replay) along the way."""
        self._apply_pending()
        target = self._step + 1
        loss = None
        while True:
            try:
                while self._gang_step < target:
                    idx = self._gang_step
                    loss = self.group.run_stateful(_elastic_step_fn, idx)[0]
                    if idx < self._step:
                        self.counters["elastic_replayed_steps_total"] += 1
                    self._gang_step += 1
                    self._gang_calls += 1
                break
            except exc.MeshGroupError as e:
                self._recover(e)
        self._step = target
        self._refresh_snapshot()
        return loss

    def run(self, steps: int) -> List[float]:
        losses = []
        for _ in range(steps):
            self._pending_steps = steps - len(losses)
            losses.append(self.step())
        self._pending_steps = 0
        return losses

    def _apply_pending(self):
        if self._notices:
            doomed = {r for r, _ in self._notices}
            self._notices = []
            self._refresh_snapshot(force=True)
            n = self._fit(self.hosts - len(doomed))
            if n < self.hosts:
                self.counters["elastic_shrinks_total"] += 1
                self.counters["elastic_notice_shrinks_total"] += 1
                logger.info("elastic: notice shrink %d -> %d hosts",
                            self.hosts, n)
                self._resize_to(n)
            self._pending_resize = None
            return
        if self._pending_resize is not None:
            n, self._pending_resize = self._pending_resize, None
            if n == self.hosts:
                return
            self._refresh_snapshot(force=True)
            if n > self.hosts:
                self.counters["elastic_grows_total"] += 1
                logger.info("elastic: grow %d -> %d hosts", self.hosts, n)
            else:
                self.counters["elastic_shrinks_total"] += 1
                self.counters["elastic_notice_shrinks_total"] += 1
            self._resize_to(n)

    def _recover(self, err: exc.MeshGroupError):
        """A gang failure mid-step: transport aborts rebuild at the same
        size (bounded); real rank death shrinks to the surviving fit.
        Either way the gang restarts from the boundary snapshot and the
        driver replays the missed indices — nothing is lost."""
        if mg.is_transport_abort(err):
            if self.counters["elastic_transport_rebuilds_total"] >= \
                    self.transport_restart_budget:
                raise err
            self.counters["elastic_transport_rebuilds_total"] += 1
            logger.warning("elastic: transport abort, rebuilding %d-host "
                           "gang in place: %s", self.hosts, err)
            self._resize_to(self.hosts)
            return
        # Peers of a dead rank surface as transport-classified TaskErrors
        # (their collective was poisoned); only non-transport failures are
        # actual corpses when sizing the surviving gang.
        ranks = getattr(err, "failed_ranks", None) or {}
        dead = [r for r, e in ranks.items()
                if not mg.is_transport_abort(e)] or list(ranks) or [0]
        failed = len(dead)
        survivors = max(self.hosts - failed, 0)
        n = self._fit(survivors)
        self.counters["elastic_shrinks_total"] += 1
        self.counters["elastic_expiry_shrinks_total"] += 1
        logger.warning("elastic: lease expiry (%d rank(s) dead), shrink "
                       "%d -> %d hosts: %s", failed, self.hosts, n, err)
        self._resize_to(n)

    # ---- introspection ----
    def params_host(self) -> Any:
        return self.group.run_rank_stateful(0, _elastic_params_host)

    def stats(self) -> Dict[str, Any]:
        return {"hosts": self.hosts, "step": self._step,
                "version": self._version, **self.counters}

    def _export_metrics(self):
        try:
            from ray_tpu.util.metrics import Counter, Gauge

            for name, val in self.counters.items():
                if name.endswith("_total"):
                    c = Counter(name, "elastic gang lifecycle")
                    delta = val - c.value()
                    if delta > 0:
                        c.inc(delta)
            Gauge("elastic_gang_hosts", "current elastic gang size").set(
                self.hosts)
        except Exception:  # driver not connected / kv unavailable
            pass

    def shutdown(self):
        self._export_metrics()
        self.group.shutdown()
