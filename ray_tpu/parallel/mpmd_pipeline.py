"""MPMD pipeline parallelism: stages as separate processes, each with its
own device mesh, activations flowing through the object store.

This is the second pipeline form SURVEY §7.8 calls for, layered on the
actor runtime (the first — intra-mesh SPMD GPipe via shard_map/ppermute —
is parallel/pipeline.py).  Reference substrate: placement groups +
collective send/recv between actors; the MPMD schedule itself follows the
GPipe paper (PAPERS.md) — no reference-code counterpart exists.

Design:

- Each ``PipelineStage`` is an actor owning one stage's params and (on a
  pod) one process group's chips.  Stage k's forward keeps its VJP
  residuals per-microbatch ON the actor, so backward needs only the
  upstream cotangent: nothing but [mb, ...] activation tensors ever
  crosses processes, and those ride the zero-copy object store.
- The driver runs the GPipe schedule by CHAINING OBJECT REFS: stage k's
  forward output ref is passed directly as stage k+1's input, so
  activations move store-to-store without touching the driver, and the
  scheduler's locality rules keep the transfer on-node where possible.
- Backward replays the chain in reverse via the stored residuals; each
  stage accumulates grads over microbatches and steps its own optimizer
  (optax) locally — exactly the per-stage-optimizer layout a multi-mesh
  pipeline wants (no global allreduce across stages).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

import ray_tpu


@ray_tpu.remote
class PipelineStage:
    """One pipeline stage process.

    stage_fn(params, x) -> y for middle stages; the LAST stage's fn is
    ``loss_fn(params, x, target) -> scalar loss``.
    """

    def __init__(self, stage_fn: Callable, init_params: Any,
                 optimizer=None):
        # Device placement is the runtime's job, not this actor's: a
        # pooled worker may already have jax imported (platform config
        # frozen), so JAX_PLATFORMS/XLA_FLAGS set here would silently
        # no-op.  On hardware, the raylet's per-worker TPU chip
        # partitioning (TPU_VISIBLE_CHIPS at spawn) gives each stage its
        # chips; in tests the conftest's CPU-mesh env does.
        import jax
        import optax

        self._jax = jax
        self.fn = stage_fn
        self.params = init_params
        self.tx = optimizer or optax.sgd(1e-2)
        self.opt_state = self.tx.init(self.params)
        self._residuals: dict = {}
        self._grad_accum = None

    # ---- schedule ops ----
    def forward(self, mb_id: int, x, target=None):
        """Run this stage on one microbatch; keep the VJP closure local.
        Returns the activation (middle) or the loss value (last)."""
        args = (x,) if target is None else (x, target)
        y, vjp_fn = self._jax.vjp(self.fn, self.params, *args)
        self._residuals[mb_id] = vjp_fn
        return np.asarray(self._jax.device_get(y))

    def backward(self, mb_id: int, dy=None):
        """Consume the stored residuals: returns the cotangent to ship
        upstream; grads accumulate locally."""
        vjp_fn = self._residuals.pop(mb_id)
        if dy is None:  # last stage: d(loss)/d(loss) = 1
            dy = np.float32(1.0)
        cotangents = vjp_fn(self._jax.numpy.asarray(dy))
        dparams, dx = cotangents[0], cotangents[1]
        if self._grad_accum is None:
            self._grad_accum = dparams
        else:
            self._grad_accum = self._jax.tree_util.tree_map(
                lambda a, b: a + b, self._grad_accum, dparams)
        return np.asarray(self._jax.device_get(dx))

    def apply_grads(self, scale: float = 1.0):
        """Optimizer step on the accumulated microbatch grads."""
        import optax

        grads = self._jax.tree_util.tree_map(
            lambda g: g * scale, self._grad_accum)
        updates, self.opt_state = self.tx.update(grads, self.opt_state,
                                                 self.params)
        self.params = optax.apply_updates(self.params, updates)
        self._grad_accum = None
        return True

    def reset(self):
        """Drop partial schedule state after a failed step — stale grad
        accumulations must not leak into the next optimizer update."""
        self._residuals.clear()
        self._grad_accum = None
        return True

    def get_params(self):
        return self._jax.device_get(self.params)

    def set_params(self, params):
        self.params = params
        self.opt_state = self.tx.init(self.params)
        return True


class MPMDPipeline:
    """Driver-side GPipe schedule over stage actors.

    ``stage_fns``: list of callables; the last must be
    loss_fn(params, x, target) -> scalar.  ``init_params``: per-stage
    pytrees.
    """

    def __init__(self, stage_fns: Sequence[Callable],
                 init_params: Sequence[Any], optimizer=None,
                 num_microbatches: int = 4,
                 stage_options: Optional[List[dict]] = None):
        n = len(stage_fns)
        if len(init_params) != n:
            raise ValueError("one params pytree per stage")
        self.num_stages = n
        self.num_microbatches = num_microbatches
        opts = stage_options or [{} for _ in range(n)]
        self.stages = [
            PipelineStage.remote(stage_fns[k], init_params[k],
                                 optimizer=optimizer, **opts[k])
            for k in range(n)
        ]

    def train_step(self, x: np.ndarray, target: np.ndarray) -> float:
        """One GPipe step: forward all microbatches through the stage
        chain (refs chain store-to-store), backward in reverse, then every
        stage steps its optimizer.  Returns the mean microbatch loss."""
        M = self.num_microbatches
        if len(x) < M:
            raise ValueError(
                f"batch of {len(x)} rows cannot fill num_microbatches={M} "
                "(an empty microbatch means a NaN loss, not an error)")
        xs = np.array_split(x, M)
        ts = np.array_split(target, M)
        try:
            # Forward: chain refs so activations never visit the driver.
            loss_refs = []
            for m in range(M):
                act = xs[m]
                for k, stage in enumerate(self.stages):
                    if k == self.num_stages - 1:
                        act = stage.forward.remote(m, act, ts[m])
                    else:
                        act = stage.forward.remote(m, act)
                loss_refs.append(act)
            losses = ray_tpu.get(loss_refs)
            # Backward: reverse chain; cotangents flow downstream→upstream.
            done = []
            for m in range(M):
                dy = None
                for k in range(self.num_stages - 1, -1, -1):
                    if dy is None:
                        dy = self.stages[k].backward.remote(m)
                    else:
                        dy = self.stages[k].backward.remote(m, dy)
                done.append(dy)
            ray_tpu.get(done)  # barrier: all residuals consumed
            ray_tpu.get([s.apply_grads.remote(1.0 / M)
                         for s in self.stages])
        except Exception:
            # A failed step leaves partial residuals/grad accumulations on
            # the stages; drop them so a retry doesn't double-apply.
            for s in self.stages:
                try:
                    ray_tpu.get(s.reset.remote())
                except Exception:
                    pass
            raise
        return float(np.mean(losses))

    def get_params(self) -> List[Any]:
        return ray_tpu.get([s.get_params.remote() for s in self.stages])

    def stop(self):
        for s in self.stages:
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
