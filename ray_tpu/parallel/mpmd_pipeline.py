"""MPMD pipeline parallelism: model stages owned by separate process
groups, activations flowing store-to-store, driven by an async 1F1B
schedule (the ROADMAP's "billion-parameter training across gangs" plane).

Reference papers: "Scaling Deep Learning Training with MPMD Pipeline
Parallelism" (arxiv 2412.14374) — stage-per-process-group pipelines with
1F1B schedules reach near-SPMD MFU at multi-billion scale — and GPipe
(arxiv 1811.06965) for the microbatch decomposition.  The first pipeline
form (intra-mesh SPMD GPipe via shard_map/ppermute) is
parallel/pipeline.py; this module is the cross-gang form.

Design (the three legs of the rebuild, vs the old naive GPipe driver):

1. **Compiled stage workers.**  Each :class:`PipelineStage` precompiles
   donated fwd/bwd/apply steps once (``train.jax``-style ``jax.jit`` with
   carry donation).  The forward runs under ``jax.vjp`` *inside* jit and
   returns the pullback as a ``jax.tree_util.Partial`` — a pytree whose
   leaves are the VJP residuals, so residuals stay ON-DEVICE between the
   separately-compiled forward and backward with zero recompute (no GPipe
   re-materialization tax) and zero per-microbatch retrace (the jit cache
   size is constant after the first step; ``stats()`` proves it).
   A stage is optionally *internally SPMD*: ``spmd_devices=N`` places its
   params replicated and its microbatch sharded over an N-device ``data``
   mesh (``rllib/utils/mesh.py`` specs), and ``zero_sharding`` composes
   the per-stage optimizer with ``parallel/zero.py`` — the apply step
   becomes a shard_map whose optimizer state is 1/N per device.  On a
   pod, each stage actor owns one process group's chips (the raylet's
   TPU partitioning), which is the MeshGroup-gang-per-stage layout.

2. **Async 1F1B schedule.**  The driver never touches tensors: stage
   k's forward output *ref* is passed directly as stage k+1's input (and
   cotangent refs chain the other way), so activations move store-to-
   store while the driver only wires the DAG.  Per-stage op order is the
   textbook 1F1B (warmup of ``num_stages-1-k`` forwards → steady 1F1B
   alternation → cooldown), enforced by actor submission order; an
   :class:`InflightWindow` of depth ``num_stages`` gates microbatch
   admission so at most ``num_stages`` microbatches are ever in flight
   (stage-side high-watermarks prove it; naive GPipe order holds all M).
   Stage k's compute overlaps k±1's transfers because the consumer pulls
   its input from the store while the producer is already running its
   next op.  :func:`mpmd_driver_sync_count` counts blocking driver↔stage
   round trips on the lockstep paths — the async schedule performs zero
   mid-step syncs (tools/perf_smoke.py ``run_mpmd_smoke`` asserts it).

3. **Pipelined step streaming + gang fault tolerance.**  Consecutive
   ``submit_step`` calls keep up to ``step_window`` steps in flight (the
   StepPipeline replay model): later steps' schedules are already queued
   on the stage actors while the oldest drains.  A stage death poisons
   the whole pipeline gang (its residuals/activations die with it), so
   recovery is all-or-nothing: every stage is torn down and respawned,
   state restores from the latest *confirmed* store-resident snapshot
   (stages snapshot params+opt every ``snapshot_interval`` steps as an
   ordinary actor op — the ref lives in the object store, the driver
   never materializes it), and the replay buffer re-dispatches every
   step since that snapshot IN ORDER — grad accumulation can't be
   corrupted because replay restarts whole steps and per-step schedules
   are deterministic.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.parallel.flow import Window as InflightWindow
from ray_tpu.parallel.mesh_group import gang_get

# Blocking driver↔stage syncs on the LOCKSTEP dispatch paths
# (train_step / get_params).  The async streaming path — submit_step +
# windowed drains — must leave it untouched: backpressure drains overlap
# with already-queued work, exactly like mesh_group.StepPipeline.
_MPMD_SYNCS = {"count": 0}


def mpmd_driver_sync_count() -> int:
    """Blocking per-step driver syncs performed by the lockstep MPMD
    paths since process start.  The async 1F1B stream adds zero."""
    return _MPMD_SYNCS["count"]


def _note_sync() -> None:
    _MPMD_SYNCS["count"] += 1


def stage_schedule(schedule: str, num_stages: int, num_microbatches: int,
                   stage: int) -> List[tuple]:
    """Per-stage op order ``[("F", m) | ("B", m), ...]``.

    ``"1f1b"``: warmup of ``num_stages - 1 - stage`` forwards, then
    strict one-forward-one-backward alternation, then backward cooldown —
    at most ``num_stages - stage`` microbatches ever hold residuals on
    this stage.  ``"gpipe"``: all forwards then all backwards (the naive
    baseline; holds all ``num_microbatches`` residuals)."""
    S, M, k = num_stages, num_microbatches, stage
    if schedule == "gpipe":
        return [("F", m) for m in range(M)] + [("B", m) for m in range(M)]
    if schedule != "1f1b":
        raise ValueError(f"schedule must be 1f1b|gpipe, got {schedule!r}")
    warm = min(S - 1 - k, M)
    ops: List[tuple] = [("F", m) for m in range(warm)]
    f, b = warm, 0
    while b < M:
        if f < M:
            ops.append(("F", f))
            f += 1
        ops.append(("B", b))
        b += 1
    return ops


@ray_tpu.remote
class PipelineStage:
    """One pipeline stage process: owns its stage's params + optimizer
    and three compiled programs (fwd / bwd / apply).

    ``stage_fn(params, x) -> y`` for middle stages; the LAST stage's fn
    is ``loss_fn(params, x, target) -> scalar loss``.  ``init_params``
    may be the params pytree itself or a zero-arg factory executed here
    (so XL-scale stages never round-trip params through the driver).
    """

    def __init__(self, stage_fn: Callable, init_params: Any,
                 optimizer=None, *, stage_id: int = 0, num_stages: int = 1,
                 is_last: Optional[bool] = None, generation: int = 0,
                 spmd_devices: int = 0, zero_sharding: str = "off",
                 restore_from: Any = None):
        import os

        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu._private import chaos

        self._jax = jax
        self._jnp = jnp
        self.fn = stage_fn
        self.stage_id = int(stage_id)
        self.num_stages = int(num_stages)
        self.is_last = (stage_id == num_stages - 1) if is_last is None \
            else bool(is_last)
        self.generation = int(generation)
        os.environ[chaos.GENERATION_ENV] = str(generation)
        self.tx = optimizer or optax.sgd(1e-2)

        params = init_params() if callable(init_params) else init_params
        # --- optional intra-stage SPMD (data-parallel over local chips)
        self._mesh = None
        self._batched = None
        self._zero = None
        self._zero_info = None
        if spmd_devices and spmd_devices > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ray_tpu.rllib.utils.mesh import data_mesh

            self._mesh = data_mesh(int(spmd_devices))
            self._repl = NamedSharding(self._mesh, P())
            self._batched = NamedSharding(self._mesh, P("data"))
            params = jax.device_put(params, self._repl)
        elif zero_sharding != "off":
            raise ValueError(
                "zero_sharding requires spmd_devices > 1 (the optimizer "
                "shards over the stage's internal data mesh)")
        self.params = params

        # --- compiled steps (built once; shape specialization is the jit
        # cache's job and stats() asserts it stays constant) ---
        donate = jax.default_backend() != "cpu"  # cpu: donation unimplemented

        def fwd_impl(params, x, *extra):
            # extra = (target,) on the last stage.  The pullback rides out
            # of jit as a tree_util.Partial: its leaves ARE the residuals,
            # device-resident until the matching bwd consumes them.
            y, vjp = jax.vjp(lambda p, x_: self.fn(p, x_, *extra), params, x)
            return y, vjp

        def bwd_impl(vjp, acc, dy):
            dparams, dx = vjp(dy)
            acc = jax.tree_util.tree_map(jnp.add, acc, dparams)
            return acc, dx

        def apply_impl(params, opt_state, acc, scale):
            grads = jax.tree_util.tree_map(lambda g: g * scale, acc)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            import optax as _optax

            return _optax.apply_updates(params, updates), opt_state

        self._fwd = jax.jit(fwd_impl)
        self._bwd = jax.jit(bwd_impl,
                            donate_argnums=(0, 1, 2) if donate else ())
        self._zeros = jax.jit(
            lambda p: jax.tree_util.tree_map(jnp.zeros_like, p))
        if zero_sharding != "off":
            self._init_zero_apply(zero_sharding, donate)
        else:
            self._apply = jax.jit(apply_impl,
                                  donate_argnums=(0, 1, 2) if donate else ())
            self.opt_state = self.tx.init(self.params)
        if restore_from is not None:
            self.restore(restore_from)

        # --- schedule state ---
        self._resid: Dict[int, tuple] = {}   # mb -> (vjp, weight, step)
        self._acc = None
        self._step_count = 0
        # --- per-step observability ---
        self._ops: List[dict] = []
        self._peak_inflight = 0
        self._act_bytes = 0

    # ---- internal helpers ----
    def _init_zero_apply(self, zero_sharding: str, donate: bool):
        """Per-stage ZeRO optimizer (parallel/zero.py): state sharded 1/N
        over the stage's internal data mesh; grads enter the shard_map
        body replicated (already accumulated over microbatches), so the
        reduce-scatter degenerates to a mean of identical rows — exact."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel import zero as zero_mod
        from ray_tpu.rllib.utils.mesh import _shard_map

        world = dict(self._mesh.shape).get("data", 1)
        zu = zero_mod.build_zero_update(
            jax.eval_shape(lambda: self.params), self.tx, world,
            zero_sharding=zero_sharding, axis_name="data")
        self._zero = zu
        self._zero_info = zero_mod.export_zero_metrics(
            zu.sharder, self.tx, zero_sharding=zero_sharding,
            quantized="off")

        def body(params, opt_block, acc, scale):
            grads = jax.tree_util.tree_map(lambda g: g * scale, acc)
            params, opt_block = zu.update(grads, opt_block, params)
            return params, opt_block

        mapped = _shard_map(body, mesh=self._mesh,
                            in_specs=(P(), zu.opt_specs, P(), P()),
                            out_specs=(P(), zu.opt_specs))
        self._apply = jax.jit(
            mapped, donate_argnums=(0, 1, 2) if donate else ())
        opt_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(self._mesh, s), zu.opt_specs,
            is_leaf=lambda s: isinstance(s, P))
        self.opt_state = jax.jit(zu.init_opt, out_shardings=opt_sh)(
            self.params)

    def _to_device(self, x):
        x = self._jnp.asarray(x)
        if self._batched is not None and getattr(x, "ndim", 0) >= 1:
            x = self._jax.device_put(x, self._batched)
        return x

    def _record(self, kind: str, step: int, mb: int, t0: float, t1: float):
        self._ops.append({"kind": kind, "stage": self.stage_id,
                          "step": step, "mb": mb, "start": t0, "end": t1})

    # ---- schedule ops (dispatched by the driver, executed in strict
    # submission order — the actor is single-threaded) ----
    def fwd(self, step: int, mb: int, x, target=None, weight: float = 1.0):
        """Forward one microbatch; the pullback (residuals) stays on this
        stage.  Middle stages return the activation (host np, rides the
        object store); the last stage returns its scalar loss."""
        from ray_tpu._private import chaos

        chaos.maybe_die("mpmd_fwd", self.stage_id)
        t_in0 = time.time()
        x_dev = self._to_device(x)
        extra = ()
        if self.is_last:
            if target is None:
                raise ValueError("last stage forward requires a target")
            extra = (self._to_device(target),)
        t0 = time.time()
        y, vjp = self._fwd(self.params, x_dev, *extra)
        y.block_until_ready()
        t1 = time.time()
        self._resid[mb] = (vjp, float(weight), step)
        self._peak_inflight = max(self._peak_inflight, len(self._resid))
        self._record("X", step, mb, t_in0, t0)
        self._record("F", step, mb, t0, t1)
        if self.is_last:
            return float(self._jax.device_get(y))
        out = np.asarray(self._jax.device_get(y))
        self._act_bytes += out.nbytes
        self._record("X", step, mb, t1, time.time())
        return out

    def bwd(self, step: int, mb: int, dy=None):
        """Backward one microbatch: consume the stored pullback, fold
        dparams into the step's accumulator, ship the input cotangent
        upstream (stage 0 returns a token — nothing upstream of it)."""
        from ray_tpu._private import chaos

        chaos.maybe_die("mpmd_bwd", self.stage_id)
        vjp, weight, fwd_step = self._resid.pop(mb)
        if fwd_step != step:
            raise RuntimeError(
                f"stage {self.stage_id}: bwd(step={step}, mb={mb}) found "
                f"residuals of step {fwd_step} — schedule corrupted")
        t_in0 = time.time()
        if dy is None:
            # Last stage: d(loss)/d(loss), scaled by this microbatch's
            # weight (its true row share of the global batch) so ragged
            # microbatches accumulate EXACT full-batch gradients.
            dy = self._jnp.asarray(weight, self._jnp.float32)
        else:
            dy = self._to_device(dy)
        if self._acc is None:
            self._acc = self._zeros(self.params)
        t0 = time.time()
        self._acc, dx = self._bwd(vjp, self._acc, dy)
        self._jax.tree_util.tree_leaves(self._acc)[0].block_until_ready()
        t1 = time.time()
        self._record("X", step, mb, t_in0, t0)
        self._record("B", step, mb, t0, t1)
        if self.stage_id == 0:
            return mb
        out = np.asarray(self._jax.device_get(dx))
        self._act_bytes += out.nbytes
        self._record("X", step, mb, t1, time.time())
        return out

    def apply_grads(self, scale: float = 1.0) -> dict:
        """Optimizer step on the accumulated grads; returns this step's
        observability payload (op spans, watermarks, jit cache sizes)."""
        from ray_tpu._private import chaos

        chaos.maybe_die("mpmd_apply", self.stage_id)
        if self._resid:
            raise RuntimeError(
                f"stage {self.stage_id}: apply with {len(self._resid)} "
                "unconsumed residuals — schedule corrupted")
        t0 = time.time()
        scale_dev = self._jnp.asarray(scale, self._jnp.float32)
        self.params, self.opt_state = self._apply(
            self.params, self.opt_state, self._acc, scale_dev)
        self._jax.tree_util.tree_leaves(self.params)[0].block_until_ready()
        t1 = time.time()
        self._acc = None
        self._step_count += 1
        self._record("A", self._step_count - 1, -1, t0, t1)
        out = self.stats()
        self._ops = []
        self._peak_inflight = 0
        return out

    def stats(self) -> dict:
        caches = {"fwd": int(self._fwd._cache_size()),
                  "bwd": int(self._bwd._cache_size()),
                  "apply": int(self._apply._cache_size())}
        out = {
            "stage": self.stage_id,
            "steps": self._step_count,
            "peak_inflight": self._peak_inflight,
            "act_bytes": self._act_bytes,
            "ops": list(self._ops),
            "busy_s": sum(o["end"] - o["start"] for o in self._ops
                          if o["kind"] in ("F", "B", "A")),
            "jit_cache": caches,
        }
        if self._zero_info is not None:
            out["zero_opt_bytes_per_replica"] = \
                self._zero_info["zero_opt_bytes_per_replica"]
            out["replicated_opt_bytes"] = \
                self._zero_info["replicated_opt_bytes"]
        return out

    # ---- lifecycle / fault tolerance ----
    def ping(self) -> int:
        return self.stage_id

    def reset(self):
        """Drop partial schedule state after a failed step — stale grad
        accumulations must not leak into the next optimizer update."""
        self._resid.clear()
        self._acc = None
        self._ops = []
        self._peak_inflight = 0
        return True

    def snapshot(self):
        """Host copy of (params, opt_state, step) — the return value
        lives in the object store; the driver holds only the ref."""
        return self._jax.device_get(
            (self.params, self.opt_state, self._step_count))

    def restore(self, snap):
        params, opt_state, step_count = snap
        put = self._jax.device_put
        if self._mesh is not None:
            self.params = put(params, self._repl)
            if self._zero is not None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                opt_sh = self._jax.tree_util.tree_map(
                    lambda s: NamedSharding(self._mesh, s),
                    self._zero.opt_specs,
                    is_leaf=lambda s: isinstance(s, P))
                self.opt_state = self._jax.tree_util.tree_map(
                    lambda x, s: put(self._jnp.asarray(x), s),
                    opt_state, opt_sh)
            else:
                self.opt_state = put(opt_state, self._repl)
        else:
            self.params = self._jax.tree_util.tree_map(
                self._jnp.asarray, params)
            self.opt_state = self._jax.tree_util.tree_map(
                self._jnp.asarray, opt_state)
        self._step_count = int(step_count)
        return True

    def get_params(self):
        return self._jax.device_get(self.params)

    def set_params(self, params):
        """Replace params (and re-init the optimizer) — compat shim."""
        self.params = self._jax.tree_util.tree_map(self._jnp.asarray, params)
        if self._mesh is not None:
            self.params = self._jax.device_put(self.params, self._repl)
        if self._zero is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            opt_sh = self._jax.tree_util.tree_map(
                lambda s: NamedSharding(self._mesh, s), self._zero.opt_specs,
                is_leaf=lambda s: isinstance(s, P))
            self.opt_state = self._jax.jit(
                self._zero.init_opt, out_shardings=opt_sh)(self.params)
        else:
            self.opt_state = self.tx.init(self.params)
        return True


class _StepRec:
    """One submitted step: the host microbatches (for replay), the refs
    the driver drains, and bookkeeping flags.  ``aux_refs`` pins every
    intermediate activation/cotangent ref until the step drains —
    dropping them at dispatch would let ref-gc free a store-resident
    activation before its consumer stage resolved it."""
    __slots__ = ("idx", "xs", "ts", "weights", "loss_refs", "apply_refs",
                 "aux_refs", "snap", "drained")

    def __init__(self, idx, xs, ts, weights, snap):
        self.idx = idx
        self.xs = xs
        self.ts = ts
        self.weights = weights
        self.loss_refs: List[Any] = []
        self.apply_refs: List[Any] = []
        self.aux_refs: List[Any] = []
        self.snap = snap
        self.drained = False


def _mpmd_metrics():
    """Lazy metric handles (internal_kv needs a connected driver)."""
    from ray_tpu.util.metrics import Counter, Gauge, Meter

    return {
        "bubble": Gauge("mpmd_bubble_fraction",
                        "1 - busy/(stages*wall) of the last drained step"),
        "steps": Counter("mpmd_steps_total", "pipeline train steps drained"),
        "replays": Counter("mpmd_replays_total",
                           "gang restarts absorbed by schedule replay"),
        "act_bytes": Meter("mpmd_activation_bytes",
                           "activation/cotangent bytes shipped through "
                           "the object store"),
        "idle": Gauge("mpmd_stage_idle_frac",
                      "per-stage idle fraction of the last drained step",
                      tag_keys=("stage",)),
        "inflight": Gauge("mpmd_peak_inflight_microbatches",
                          "peak microbatches holding residuals on any "
                          "stage in the last drained step"),
    }


class MPMDPipeline:
    """Driver-side async 1F1B schedule over compiled stage actors.

    ``stage_fns``: list of callables; the last must be
    ``loss_fn(params, x, target) -> scalar``.  ``init_params``: per-stage
    pytrees OR zero-arg factories (run on the stage).  ``stage_options``:
    per-stage PipelineStage kwargs (``spmd_devices``, ``zero_sharding``).

    Lockstep use (drop-in for the old driver)::

        pipe = MPMDPipeline([f0, loss_fn], [p0, p1], num_microbatches=4)
        loss = pipe.train_step(x, t)        # one blocking sync per step

    Streaming use (the zero-sync hot path)::

        for x, t in batches:
            pipe.submit_step(x, t)          # ≤ step_window in flight
        losses = pipe.flush()               # [(step_idx, loss), ...]

    Fault tolerance: ``max_restarts > 0`` arms snapshotting (every
    ``snapshot_interval`` steps, store-resident) and replay — a stage
    death respawns every stage from the latest confirmed snapshot and
    re-dispatches every step since, in order."""

    def __init__(self, stage_fns: Sequence[Callable],
                 init_params: Sequence[Any], optimizer=None,
                 num_microbatches: int = 4,
                 stage_options: Optional[List[dict]] = None, *,
                 schedule: str = "1f1b", step_window: int = 2,
                 max_restarts: int = 0, snapshot_interval: int = 1,
                 drain_timeout: Optional[float] = None,
                 export_metrics: bool = True):
        n = len(stage_fns)
        if len(init_params) != n:
            raise ValueError("one params pytree per stage")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"schedule must be 1f1b|gpipe, got {schedule!r}")
        self.num_stages = n
        self.num_microbatches = int(num_microbatches)
        self.schedule = schedule
        self.step_window = max(1, int(step_window))
        self.max_restarts = int(max_restarts)
        self.snapshot_interval = max(1, int(snapshot_interval))
        self.drain_timeout = drain_timeout
        self.restart_count = 0
        self._stage_fns = list(stage_fns)
        self._init_params = list(init_params)
        self._optimizer = optimizer
        self._stage_opts = list(stage_options or [{} for _ in range(n)])
        self._generation = 0
        self.stages: List[Any] = []
        self._spawn_stages(restore_refs=None)

        self._window: InflightWindow = InflightWindow(self.step_window)
        self._replay: collections.deque = collections.deque()  # _StepRec
        self._results: List[tuple] = []
        self._next_idx = 0
        self._snap: Optional[tuple] = None          # (idx, [refs])
        self._pending_snap: Optional[tuple] = None  # (idx, [refs])
        self._last_report: Optional[dict] = None
        self._act_bytes_total = 0
        self._busy_total = 0.0
        self._wall_total = 0.0
        self._peak_window = 0
        self._metrics = None
        if export_metrics:
            try:
                self._metrics = _mpmd_metrics()
            except Exception:
                self._metrics = None

    # ---- gang lifecycle ----
    def _spawn_stages(self, restore_refs: Optional[List[Any]]) -> None:
        self.stages = [
            PipelineStage.remote(
                self._stage_fns[k], self._init_params[k],
                optimizer=self._optimizer, stage_id=k,
                num_stages=self.num_stages, generation=self._generation,
                restore_from=None if restore_refs is None
                else restore_refs[k],
                **self._stage_opts[k])
            for k in range(self.num_stages)
        ]

    def _teardown_stages(self) -> None:
        for s in self.stages:
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
        self.stages = []

    def _dead_stages(self, deadline: float = 15.0) -> List[int]:
        """Bounded ping fan-out; returns the stage ids that are dead or
        unresponsive (empty list = the gang looks healthy)."""
        try:
            gang_get([s.ping.remote() for s in self.stages],
                     timeout=deadline)
            return []
        except exc.MeshGroupError as e:
            return sorted(e.failed_ranks)
        except Exception:
            return list(range(self.num_stages))

    # ---- schedule dispatch (pure ref wiring — no tensors, no waits) ----
    def _dispatch_step(self, rec: _StepRec) -> None:
        if rec.snap:
            refs = [s.snapshot.remote() for s in self.stages]
            self._pending_snap = (rec.idx, refs)
        S, M = self.num_stages, len(rec.xs)
        queues = [collections.deque(stage_schedule(self.schedule, S, M, k))
                  for k in range(S)]
        acts: List[Dict[int, Any]] = [dict() for _ in range(S)]
        cots: List[Dict[int, Any]] = [dict() for _ in range(S)]
        window = InflightWindow(S if self.schedule == "1f1b" else M)
        rec.loss_refs, rec.apply_refs = [], []
        remaining = sum(len(q) for q in queues)
        while remaining:
            progressed = False
            for k in range(S):
                q = queues[k]
                while q:
                    op, m = q[0]
                    if op == "F":
                        src = rec.xs[m] if k == 0 else acts[k - 1].get(m)
                        if src is None:
                            break
                        if k == 0:
                            window.append(m)
                            self._peak_window = max(self._peak_window,
                                                    len(window))
                            if window.over_depth:
                                raise RuntimeError(
                                    "1F1B scheduler admitted more than "
                                    f"{window.depth} microbatches")
                        if k == S - 1:
                            ref = self.stages[k].fwd.remote(
                                rec.idx, m, src, rec.ts[m],
                                float(rec.weights[m]))
                            rec.loss_refs.append(ref)
                        else:
                            ref = self.stages[k].fwd.remote(rec.idx, m, src)
                            acts[k][m] = ref
                    else:  # "B"
                        if k == S - 1:
                            dy = None
                        else:
                            dy = cots[k + 1].get(m)
                            if dy is None:
                                break
                        if k == 0:
                            window.remove(m)
                        if dy is None:
                            ref = self.stages[k].bwd.remote(rec.idx, m)
                        else:
                            ref = self.stages[k].bwd.remote(rec.idx, m, dy)
                        cots[k][m] = ref
                    q.popleft()
                    remaining -= 1
                    progressed = True
            if not progressed:
                raise RuntimeError(
                    f"{self.schedule} schedule deadlocked with "
                    f"{remaining} ops pending (S={S}, M={M})")
        rec.apply_refs = [s.apply_grads.remote() for s in self.stages]
        rec.aux_refs = [r for d in acts + cots for r in d.values()]

    def _split_batch(self, x, target):
        M = self.num_microbatches
        if len(x) < M:
            raise ValueError(
                f"batch of {len(x)} rows cannot fill num_microbatches={M} "
                "(an empty microbatch means a NaN loss, not an error)")
        if len(x) != len(target):
            raise ValueError("x and target row counts differ")
        xs = np.array_split(x, M)
        ts = np.array_split(target, M)
        # True per-microbatch weights: grad accumulation and the reported
        # loss weight each microbatch by its ACTUAL row share, so ragged
        # splits (len(x) % M != 0) match the single-process full-batch
        # gradients exactly (the old driver weighted all equally).
        weights = np.asarray([len(xb) for xb in xs], np.float64) / len(x)
        return xs, ts, weights

    # ---- streaming API (the zero-sync hot path) ----
    def submit_step(self, x: np.ndarray, target: np.ndarray) -> int:
        """Dispatch one full 1F1B step schedule asynchronously; blocks
        (draining the oldest step) only once more than ``step_window``
        steps are in flight.  Returns the step index."""
        xs, ts, weights = self._split_batch(x, target)
        idx = self._next_idx
        self._next_idx += 1
        snap = self.max_restarts > 0 and (
            self._snap is None and self._pending_snap is None
            or (self._pending_snap is None
                and idx - self._snap[0] >= self.snapshot_interval))
        rec = _StepRec(idx, xs, ts, weights, snap)
        self._dispatch_step(rec)
        self._replay.append(rec)
        self._window.append(rec)
        while self._window.over_depth:
            self._drain_one()
        return idx

    def flush(self) -> List[tuple]:
        """Drain every in-flight step; returns all accumulated
        ``(step_idx, loss)`` pairs (destructive read)."""
        while self._window:
            self._drain_one()
        out, self._results = self._results, []
        return out

    def train_step(self, x: np.ndarray, target: np.ndarray) -> float:
        """Lockstep step (compat API): submit + drain everything, return
        THIS step's weighted mean microbatch loss."""
        _note_sync()
        idx = self.submit_step(x, target)
        drained = dict(self.flush())
        return drained[idx]

    # ---- drain + recovery ----
    def _drain_one(self) -> None:
        rec = self._window.peek()
        while True:
            try:
                vals = gang_get(rec.loss_refs + rec.apply_refs,
                                timeout=self.drain_timeout)
                break
            except exc.MeshGroupError as e:
                self._recover(e)
            except exc.RayTpuError:
                # A user exception — or a task poisoned by an upstream
                # stage death (surfaces as a TaskError, not an actor
                # error).  Disambiguate with a bounded ping fan-out.
                dead = self._dead_stages()
                if dead:
                    self._recover(exc.MeshGroupError(
                        f"pipeline stage(s) {dead} died mid-step",
                        failed_ranks={d: exc.ActorDiedError(
                            f"stage {d} unresponsive") for d in dead}))
                    continue
                self._abort()
                raise
        M = len(rec.loss_refs)
        losses, stage_stats = vals[:M], vals[M:]
        loss = float(np.dot(rec.weights, np.asarray(losses, np.float64)))
        self._window.popleft()
        rec.drained = True
        rec.aux_refs = []  # consumers finished: release the pins
        self._results.append((rec.idx, loss))
        self._ingest_stats(rec, stage_stats)
        # Snapshot confirmation: this step drained, so every op queued
        # before it — including the snapshot — executed.
        if self._pending_snap is not None and \
                rec.idx >= self._pending_snap[0]:
            self._snap = self._pending_snap
            self._pending_snap = None
            while self._replay and self._replay[0].idx < self._snap[0]:
                self._replay.popleft()
        elif self.max_restarts == 0:
            while self._replay and self._replay[0].drained:
                self._replay.popleft()

    def _recover(self, cause: exc.MeshGroupError) -> None:
        """All-or-nothing gang restart + in-order schedule replay."""
        if self.restart_count >= self.max_restarts:
            cause.restarts = self.restart_count
            self._abort(teardown=False)
            raise cause
        self.restart_count += 1
        self._generation += 1
        self._teardown_stages()
        restore = list(self._snap[1]) if self._snap is not None else None
        self._pending_snap = None  # its refs died with the old gang
        self._spawn_stages(restore_refs=restore)
        for rec in self._replay:
            if rec.snap and self._snap is not None \
                    and rec.idx <= self._snap[0]:
                rec.snap = False  # already restored from this snapshot
            self._dispatch_step(rec)
        if self._metrics is not None:
            try:
                self._metrics["replays"].inc()
            except Exception:
                pass

    def _abort(self, teardown: bool = False) -> None:
        """Drop in-flight schedule state after an unrecoverable error so
        a retry doesn't double-apply; stages reset their accumulators."""
        self._window.clear()
        self._replay.clear()
        self._pending_snap = None
        if teardown:
            self._teardown_stages()
            return
        for s in self.stages:
            try:
                ray_tpu.get(s.reset.remote())
            except Exception:
                pass

    # ---- observability ----
    def _ingest_stats(self, rec: _StepRec, stage_stats: Sequence[dict]):
        try:
            ops = [o for st in stage_stats for o in st["ops"]]
            wall = (max(o["end"] for o in ops)
                    - min(o["start"] for o in ops)) if ops else 0.0
            busy = [st["busy_s"] for st in stage_stats]
            bubble = 1.0 - sum(busy) / (self.num_stages * wall) \
                if wall > 0 else 0.0
            act_bytes = sum(st["act_bytes"] for st in stage_stats) \
                - self._act_bytes_total
            self._act_bytes_total += act_bytes
            self._busy_total += sum(busy)
            self._wall_total += wall
            self._last_report = {
                "step": rec.idx,
                "bubble_fraction": bubble,
                "wall_s": wall,
                "busy_s": busy,
                "peak_inflight": {st["stage"]: st["peak_inflight"]
                                  for st in stage_stats},
                "jit_cache": {st["stage"]: st["jit_cache"]
                              for st in stage_stats},
                "act_bytes": act_bytes,
                "ops": {st["stage"]: st["ops"] for st in stage_stats},
            }
            from ray_tpu._private import profiling

            for o in ops:
                profiling.record_span(
                    {"F": "mpmd_stage_fwd", "B": "mpmd_stage_bwd",
                     "A": "mpmd_stage_apply", "X": "mpmd_stage_transfer"}
                    [o["kind"]], o["start"], o["end"], stage=o["stage"],
                    step=o["step"], mb=o["mb"])
            if self._metrics is not None:
                m = self._metrics
                m["bubble"].set(bubble)
                m["steps"].inc()
                m["act_bytes"].mark(float(act_bytes))
                m["inflight"].set(float(max(
                    st["peak_inflight"] for st in stage_stats)))
                for st, b in zip(stage_stats, busy):
                    idle = 1.0 - b / wall if wall > 0 else 0.0
                    m["idle"].set(idle, tags={"stage": str(st["stage"])})
        except Exception:
            pass  # observability is best-effort, never the step path

    def last_step_report(self) -> Optional[dict]:
        """Observability payload of the most recently drained step."""
        return self._last_report

    def stats(self) -> dict:
        rep = self._last_report or {}
        return {
            "num_stages": self.num_stages,
            "num_microbatches": self.num_microbatches,
            "schedule": self.schedule,
            "steps_submitted": self._next_idx,
            "steps_inflight": len(self._window),
            "restarts": self.restart_count,
            "bubble_fraction": rep.get("bubble_fraction"),
            "peak_inflight": rep.get("peak_inflight"),
            "jit_cache": rep.get("jit_cache"),
            "activation_bytes": self._act_bytes_total,
            "act_gb_per_s": (self._act_bytes_total / self._wall_total / 1e9
                             if self._wall_total > 0 else 0.0),
            "driver_peak_window": self._peak_window,
        }

    # ---- params access (lockstep paths) ----
    def get_params(self) -> List[Any]:
        _note_sync()
        self.flush()
        return gang_get([s.get_params.remote() for s in self.stages])

    def stop(self):
        try:
            if self._window:
                self.flush()
        except Exception:
            pass
        self._teardown_stages()

    def __enter__(self) -> "MPMDPipeline":
        return self

    def __exit__(self, exc_type, exc_val, tb) -> None:
        if exc_type is not None:
            self._abort(teardown=True)
        else:
            self.stop()
