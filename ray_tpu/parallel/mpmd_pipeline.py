"""MPMD pipeline parallelism: model stages owned by separate process
groups, activations flowing store-to-store, driven by an async 1F1B
schedule (the ROADMAP's "billion-parameter training across gangs" plane).

Reference papers: "Scaling Deep Learning Training with MPMD Pipeline
Parallelism" (arxiv 2412.14374) — stage-per-process-group pipelines with
1F1B schedules reach near-SPMD MFU at multi-billion scale — GPipe
(arxiv 1811.06965) for the microbatch decomposition, Megatron-LM's
interleaved virtual-stage schedule for the bubble shrink, and EQuARX
(arxiv 2506.17615) for the block-scaled int8 wire format.  The first
pipeline form (intra-mesh SPMD GPipe via shard_map/ppermute) is
parallel/pipeline.py; this module is the cross-gang form.

Design — the composed 3D plane (pipeline x SPMD x ZeRO), four legs:

1. **Compiled stage workers.**  Each pipeline stage precompiles donated
   fwd/bwd/apply steps per owned model chunk (:class:`StageCore`).  The
   forward runs under ``jax.vjp`` *inside* jit and returns the pullback
   as a ``jax.tree_util.Partial`` — a pytree whose leaves are the VJP
   residuals, so residuals stay ON-DEVICE between the separately-compiled
   forward and backward with zero recompute and zero per-microbatch
   retrace (jit cache sizes are constant after step one; ``stats()``
   proves it).  A stage is optionally *internally SPMD*:
   ``spmd_devices=N`` places its params replicated and its microbatch
   sharded over an N-device ``data`` mesh, and ``zero_sharding`` composes
   the per-stage optimizer with ``parallel/zero.py`` (1/N optimizer
   state per device).

2. **Multi-host stage gangs.**  With ``gang_hosts=G`` each stage is a
   :class:`~ray_tpu.parallel.mesh_group.MeshGroup` gang of G worker
   processes forming ONE ``jax.distributed`` SPMD world (the MPMD
   paper's deployment shape): the stage's params are replicated across
   the gang, each microbatch is sharded over every gang device, grads
   all-reduce inside the compiled backward, and ZeRO shards the
   optimizer 1/(G*devices) — the stage's internal SPMD/ZeRO genuinely
   spans hosts.  Rank r of stage k ships its *slice* of the activation
   store-to-store to rank r of stage k+1 (cotangents chain back the
   same edges), so the ref chain crosses hosts over the transfer plane
   and a gang-rank death exercises the real node-death path.  Stage ops
   ride the MeshWorker pipeline sequence gate, so every rank executes
   the identical schedule in the identical order — compiled collectives
   can never interleave across microbatches.

3. **Async interleaved 1F1B schedule.**  The driver never touches
   tensors: chunk c's forward output *ref* is chunk c+1's input (and
   cotangent refs chain back), so activations move store-to-store while
   the driver only wires the DAG.  ``virtual_per_rank=v`` assigns v
   non-contiguous model chunks to each physical stage (chunk c lives on
   stage ``c % S``) and the per-stage op order interleaves them
   (Megatron's interleaved 1F1B), cutting the pipeline bubble from
   ``(S-1)/(M+S-1)`` toward the ``1/(v*M)`` envelope —
   :func:`simulate_schedule` predicts it analytically and the
   ``mpmd_bubble_fraction`` gauge measures it.  ``v=1`` keeps the exact
   textbook 1F1B order (warmup ``S-1-k`` → steady alternation →
   cooldown; at most ``S-k`` residual sets per stage).

4. **Quantized inter-stage wire.**  ``wire_dtype="int8"`` serializes
   activations AND cotangents through the EQuARX block-scaled int8
   format (``ops/collectives.py``): the producer quantizes inside its
   compiled step (one f32 scale per block, block auto-sized to divide
   the hidden dim so no padding ships), int8 payloads + scales ride the
   same ref the fp32 wire used, and the consumer dequantizes inside its
   compiled step — wire bytes drop ~4x on the slowest link of the
   pipeline.  ``wire_dtype="fp32"`` (default) is the bit-stable
   fallback; the ``mpmd_wire_bytes`` meter counts actual shipped bytes
   vs the logical fp32 bytes either way.

Step streaming + fault tolerance are unchanged from the single-actor
plane: ``submit_step`` keeps ``step_window`` whole steps in flight,
``max_restarts > 0`` arms store-resident snapshots and a stage (or gang
rank) death tears down ALL stages, respawns with a generation bump,
restores from the confirmed snapshot and re-dispatches every step since
IN ORDER.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.parallel.flow import Window as InflightWindow
from ray_tpu.parallel.mesh_group import MeshGroup, gang_get

# Blocking driver↔stage syncs on the LOCKSTEP dispatch paths
# (train_step / get_params).  The async streaming path — submit_step +
# windowed drains — must leave it untouched: backpressure drains overlap
# with already-queued work, exactly like mesh_group.StepPipeline.
_MPMD_SYNCS = {"count": 0}


def mpmd_driver_sync_count() -> int:
    """Blocking per-step driver syncs performed by the lockstep MPMD
    paths since process start.  The async 1F1B stream adds zero."""
    return _MPMD_SYNCS["count"]


def _note_sync() -> None:
    _MPMD_SYNCS["count"] += 1


# ---------------------------------------------------------------------------
# Schedules
# ---------------------------------------------------------------------------

def stage_schedule(schedule: str, num_stages: int, num_microbatches: int,
                   stage: int, virtual_per_rank: int = 1) -> List[tuple]:
    """Per-stage op order ``[("F"|"B", chunk, mb), ...]``.

    ``chunk`` is the GLOBAL virtual-stage index in ``[0, S*v)``; physical
    stage ``k`` owns the non-contiguous chunks ``{k, k+S, ..., k+(v-1)S}``
    (Megatron's interleaved assignment — for ``v=1`` chunk == stage).

    ``"1f1b"``, ``v=1``: textbook warmup of ``S - 1 - stage`` forwards,
    strict one-forward-one-backward alternation, backward cooldown — at
    most ``S - stage`` microbatches ever hold residuals on this stage.
    ``v>1``: the interleaved schedule — microbatches advance in groups of
    S through each chunk slot, warmup is ``2*(S-1-k) + (v-1)*S`` forward
    ops, then strict 1F1B alternation; requires ``M % S == 0`` (the
    Megatron constraint — groups must tile the microbatch count).
    ``"gpipe"``: all forwards (chunks ascending) then all backwards
    (descending) — the naive baseline; holds every residual."""
    S, M, k, v = num_stages, num_microbatches, stage, virtual_per_rank
    if v < 1:
        raise ValueError(f"virtual_per_rank must be >= 1, got {v}")
    if schedule == "gpipe":
        ops = [("F", slot * S + k, m) for slot in range(v) for m in range(M)]
        ops += [("B", slot * S + k, m) for slot in reversed(range(v))
                for m in range(M)]
        return ops
    if schedule != "1f1b":
        raise ValueError(f"schedule must be 1f1b|gpipe, got {schedule!r}")
    if v == 1:
        warm = min(S - 1 - k, M)
        ops = [("F", k, m) for m in range(warm)]
        f, b = warm, 0
        while b < M:
            if f < M:
                ops.append(("F", k, f))
                f += 1
            ops.append(("B", k, b))
            b += 1
        return ops
    if M % S != 0:
        raise ValueError(
            f"interleaved schedule (virtual_per_rank={v}) requires "
            f"num_microbatches % num_stages == 0, got M={M}, S={S}")
    total = M * v

    def f_op(i: int) -> tuple:
        grp, within = divmod(i, S * v)
        slot, moff = divmod(within, S)
        return ("F", slot * S + k, grp * S + moff)

    def b_op(i: int) -> tuple:
        grp, within = divmod(i, S * v)
        slot = (v - 1) - within // S
        return ("B", slot * S + k, grp * S + within % S)

    warm = min(2 * (S - 1 - k) + (v - 1) * S, total)
    ops = [f_op(i) for i in range(warm)]
    f, b = warm, 0
    while b < total:
        if f < total:
            ops.append(f_op(f))
            f += 1
        ops.append(b_op(b))
        b += 1
    return ops


def simulate_schedule(schedule: str, num_stages: int, num_microbatches: int,
                      virtual_per_rank: int = 1, *, cost_f: float = 1.0,
                      cost_b: float = 2.0) -> dict:
    """Event-driven unit-cost simulation of a pipeline schedule.

    Validates feasibility (raises on deadlock — an op whose producer can
    never run) and returns the analytic envelope the real run should
    approach: ``makespan``, per-stage busy time, and ``bubble_fraction``
    = ``1 - sum(busy) / (S * makespan)``.  Used by tests to assert the
    interleaved schedule strictly beats the non-interleaved one at equal
    (S, M) without timing-sensitive measurements, and by docs for the
    when-to-interleave guidance."""
    S, M, v = num_stages, num_microbatches, virtual_per_rank
    C = S * v
    queues = [collections.deque(
        stage_schedule(schedule, S, M, k, v)) for k in range(S)]
    total = sum(len(q) for q in queues)
    done: Dict[tuple, float] = {}   # (op, chunk, mb) -> finish time
    free = [0.0] * S
    busy = [0.0] * S
    while total:
        progressed = False
        for k in range(S):
            q = queues[k]
            while q:
                op, c, m = q[0]
                if op == "F":
                    dep = None if c == 0 else ("F", c - 1, m)
                else:
                    dep = None if c == C - 1 else ("B", c + 1, m)
                if dep is not None and dep not in done:
                    break
                ready = done.get(dep, 0.0) if dep is not None else 0.0
                cost = cost_f if op == "F" else cost_b
                start = max(free[k], ready)
                done[(op, c, m)] = start + cost
                free[k] = start + cost
                busy[k] += cost
                q.popleft()
                total -= 1
                progressed = True
        if not progressed:
            raise RuntimeError(
                f"{schedule} schedule deadlocked (S={S}, M={M}, v={v}): "
                f"{total} ops can never run")
    makespan = max(free)
    return {
        "makespan": makespan,
        "busy": busy,
        "bubble_fraction": 1.0 - sum(busy) / (S * makespan)
        if makespan > 0 else 0.0,
    }


# ---------------------------------------------------------------------------
# StageCore: the in-process stage engine (shared by the solo actor and
# the multi-host gang ranks)
# ---------------------------------------------------------------------------

class StageCore:
    """One pipeline stage's compiled programs + schedule state, for the
    v model chunks this physical stage owns.

    ``chunk_fns[slot]`` is the fn of global chunk ``slot * S + stage_id``
    — ``fn(params, x)`` for middle chunks, ``loss_fn(params, x, target)``
    for the last global chunk.  ``chunk_params[slot]`` may be pytrees or
    zero-arg factories executed here (XL-scale params never round-trip
    through the driver).

    Mesh layout: ``gang_size > 1`` means this process is rank
    ``gang_rank`` of a ``jax.distributed`` world — the mesh spans EVERY
    device of the gang (multi-host SPMD; microbatch slices arrive/leave
    per rank).  Otherwise ``spmd_devices=N`` builds a local N-device
    data mesh (single-host SPMD), and 0 runs single-device.

    ``wire_dtype="int8"``: non-first inputs and non-last outputs cross
    the stage boundary as block-scaled int8 (quantize/dequantize INSIDE
    the compiled steps; the block is auto-sized to divide the trailing
    dim so no padding ships).  Cotangents use the producing edge's
    format symmetrically."""

    def __init__(self, chunk_fns: Sequence[Callable],
                 chunk_params: Sequence[Any], optimizer=None, *,
                 stage_id: int = 0, num_stages: int = 1,
                 virtual_per_rank: int = 1, wire_dtype: str = "fp32",
                 wire_block: int = 256, spmd_devices: int = 0,
                 zero_sharding: str = "off", gang_rank: int = 0,
                 gang_size: int = 1, restore_from: Any = None):
        import jax
        import jax.numpy as jnp
        import optax

        self._jax = jax
        self._jnp = jnp
        self.stage_id = int(stage_id)
        self.num_stages = int(num_stages)
        self.v = int(virtual_per_rank)
        self.num_chunks = self.num_stages * self.v
        self.gang_rank = int(gang_rank)
        self.gang_size = int(gang_size)
        if wire_dtype not in ("fp32", "int8"):
            raise ValueError(f"wire_dtype must be fp32|int8, "
                             f"got {wire_dtype!r}")
        self.wire_dtype = wire_dtype
        self.wire_block = int(wire_block)
        if len(chunk_fns) != self.v or len(chunk_params) != self.v:
            raise ValueError(
                f"stage {stage_id} expected {self.v} chunk fns/params, "
                f"got {len(chunk_fns)}/{len(chunk_params)}")
        self.fns = list(chunk_fns)
        self.tx = optimizer or optax.sgd(1e-2)

        # --- mesh: gang-global > local SPMD > single device ---
        self._mesh = None
        self._repl = None
        self._batched = None
        if self.gang_size > 1:
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            devs = jax.devices()  # spans the gang post-bootstrap
            self._mesh = Mesh(np.array(devs), ("data",))
            self._repl = NamedSharding(self._mesh, P())
            self._batched = NamedSharding(self._mesh, P("data"))
        elif spmd_devices and spmd_devices > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ray_tpu.rllib.utils.mesh import data_mesh

            self._mesh = data_mesh(int(spmd_devices))
            self._repl = NamedSharding(self._mesh, P())
            self._batched = NamedSharding(self._mesh, P("data"))
        elif zero_sharding != "off":
            raise ValueError(
                "zero_sharding requires spmd_devices > 1 or gang_hosts > 1 "
                "(the optimizer shards over the stage's data mesh)")
        self.params = [self._put_repl(p() if callable(p) else p)
                       for p in chunk_params]

        # --- compiled steps, one triplet per owned chunk ---
        donate = jax.default_backend() != "cpu"  # cpu: donation unimplemented
        self._zeros = jax.jit(
            lambda p: jax.tree_util.tree_map(jnp.zeros_like, p))
        self._fwd: List[Any] = []
        self._bwd: List[Any] = []
        self._apply: List[Any] = []
        self._zero = [None] * self.v
        self._zero_info = [None] * self.v
        self.opt_state: List[Any] = []
        for slot in range(self.v):
            self._build_chunk(slot, donate, zero_sharding)
        if restore_from is not None:
            self.restore(restore_from)

        # --- schedule state ---
        self._resid: Dict[tuple, tuple] = {}  # (slot, mb) -> (vjp, w, step)
        self._acc: List[Any] = [None] * self.v
        self._step_count = 0
        # --- per-step observability ---
        self._ops: List[dict] = []
        self._peak_inflight = 0
        self._act_bytes = 0    # logical fp32 boundary bytes
        self._wire_bytes = 0   # bytes actually shipped through the store

    # ---- chunk program construction ----
    def _global_chunk(self, slot: int) -> int:
        return slot * self.num_stages + self.stage_id

    def _is_last_chunk(self, slot: int) -> bool:
        return self._global_chunk(slot) == self.num_chunks - 1

    def _wire_block_for(self, n: int) -> int:
        """Largest block <= wire_block that divides n: the quantized
        payload then pads nothing — bytes on the wire are exactly
        ``n + 4 * n/block`` per fp32 element row."""
        wb = max(1, self.wire_block)
        if n <= wb:
            return n
        for d in range(wb, 0, -1):
            if n % d == 0:
                return d
        return n

    def _build_chunk(self, slot: int, donate: bool, zero_sharding: str):
        jax, jnp = self._jax, self._jnp
        from ray_tpu.ops import collectives as coll

        gc = self._global_chunk(slot)
        first = gc == 0
        last = self._is_last_chunk(slot)
        in_wire = (not first) and self.wire_dtype == "int8"
        out_wire = (not last) and self.wire_dtype == "int8"
        fn = self.fns[slot]
        core = self

        def dequant(q, s):
            return coll.dequantize_block_int8(q, s, q.shape[-1], jnp.float32)

        def quant(y):
            blk = core._wire_block_for(y.shape[-1])
            q, s = coll.quantize_block_int8(y, blk)
            return {"q": q, "s": s}

        def fwd_impl(params, *args):
            if in_wire:
                x, extra = dequant(args[0], args[1]), args[2:]
            else:
                x, extra = args[0], args[1:]
            y, vjp = jax.vjp(lambda p, xx: fn(p, xx, *extra), params, x)
            if out_wire:
                return quant(y), vjp
            return y, vjp

        def bwd_impl(vjp, acc, *dyargs):
            dy = dequant(dyargs[0], dyargs[1]) if out_wire else dyargs[0]
            dparams, dx = vjp(dy)
            acc = jax.tree_util.tree_map(jnp.add, acc, dparams)
            if first:
                return acc, jnp.zeros((), jnp.int32)
            if in_wire:
                return acc, quant(dx)
            return acc, dx

        def apply_impl(params, opt_state, acc, scale):
            import optax as _optax

            grads = jax.tree_util.tree_map(lambda g: g * scale, acc)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return _optax.apply_updates(params, updates), opt_state

        n_dy = 2 if out_wire else 1
        self._fwd.append(jax.jit(fwd_impl))
        self._bwd.append(jax.jit(
            bwd_impl, donate_argnums=tuple(range(2 + n_dy)) if donate
            else ()))
        if zero_sharding != "off":
            self._build_zero_apply(slot, zero_sharding, donate)
        else:
            self._apply.append(jax.jit(
                apply_impl, donate_argnums=(0, 1, 2) if donate else ()))
            self.opt_state.append(self.tx.init(self.params[slot]))

    def _build_zero_apply(self, slot: int, zero_sharding: str, donate: bool):
        """Per-chunk ZeRO optimizer (parallel/zero.py): state sharded 1/N
        over the stage's data mesh (which spans the whole gang when
        gang_size > 1); grads enter the shard_map body replicated — the
        cross-device mean already happened in the compiled backward — so
        the reduce-scatter degenerates to a mean of identical rows."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel import zero as zero_mod
        from ray_tpu.rllib.utils.mesh import _shard_map

        world = dict(self._mesh.shape).get("data", 1)
        zu = zero_mod.build_zero_update(
            jax.eval_shape(lambda: self.params[slot]), self.tx, world,
            zero_sharding=zero_sharding, axis_name="data")
        self._zero[slot] = zu
        self._zero_info[slot] = zero_mod.export_zero_metrics(
            zu.sharder, self.tx, zero_sharding=zero_sharding,
            quantized="off")

        def body(params, opt_block, acc, scale):
            grads = jax.tree_util.tree_map(lambda g: g * scale, acc)
            params, opt_block = zu.update(grads, opt_block, params)
            return params, opt_block

        mapped = _shard_map(body, mesh=self._mesh,
                            in_specs=(P(), zu.opt_specs, P(), P()),
                            out_specs=(P(), zu.opt_specs))
        self._apply.append(jax.jit(
            mapped, donate_argnums=(0, 1, 2) if donate else ()))
        opt_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(self._mesh, s), zu.opt_specs,
            is_leaf=lambda s: isinstance(s, P))
        self.opt_state.append(jax.jit(zu.init_opt, out_shardings=opt_sh)(
            self.params[slot]))

    # ---- host<->device plumbing (gang-aware) ----
    def _put_repl(self, tree):
        """Place a host pytree replicated on the stage mesh.  Multi-host:
        ``make_array_from_callback`` materializes only this process's
        addressable shards (every rank feeds identical host values)."""
        jax, jnp = self._jax, self._jnp
        if self._mesh is None:
            return jax.tree_util.tree_map(jnp.asarray, tree)
        if self.gang_size > 1:
            def put(a):
                host = np.asarray(a)
                return jax.make_array_from_callback(
                    host.shape, self._repl, lambda idx, _h=host: _h[idx])

            return jax.tree_util.tree_map(put, tree)
        return jax.device_put(
            jax.tree_util.tree_map(jnp.asarray, tree), self._repl)

    def _to_batched(self, x):
        """Host microbatch (this rank's slice) -> device, sharded over
        the stage's data axis.  Multi-host: the local slice becomes this
        process's rows of ONE global array."""
        jax, jnp = self._jax, self._jnp
        x = np.asarray(x)
        if self._mesh is None or x.ndim < 1:
            return jnp.asarray(x)
        if self.gang_size > 1:
            return jax.make_array_from_process_local_data(self._batched, x)
        return jax.device_put(jnp.asarray(x), self._batched)

    def _to_host(self, arr):
        """Device array -> this rank's host view: full array when fully
        addressable, the rank's concatenated row shards otherwise (the
        per-rank activation slice that ships downstream)."""
        jax = self._jax
        if self.gang_size <= 1 or getattr(arr, "is_fully_addressable", True):
            return np.asarray(jax.device_get(arr))
        seen: Dict[tuple, np.ndarray] = {}
        for s in arr.addressable_shards:
            key = tuple((sl.start or 0, sl.stop or -1) for sl in s.index)
            seen.setdefault(key, np.asarray(s.data))
        parts = [seen[k] for k in sorted(seen)]
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)

    def _wire_in(self, payload, first: bool):
        """Host wire payload -> device args tuple for the compiled fwd."""
        if isinstance(payload, dict) and "q" in payload:
            return (self._to_batched(payload["q"]),
                    self._to_batched(payload["s"]))
        return (self._to_batched(payload),)

    def _wire_out(self, y):
        """Device boundary value -> host wire payload + byte accounting."""
        if isinstance(y, dict) and "q" in y:
            q = self._to_host(y["q"])
            s = self._to_host(y["s"])
            self._act_bytes += q.size * 4          # logical fp32 bytes
            self._wire_bytes += q.nbytes + s.nbytes
            return {"q": q, "s": s}
        out = self._to_host(y)
        self._act_bytes += out.nbytes
        self._wire_bytes += out.nbytes
        return out

    def _record(self, kind: str, step: int, mb: int, t0: float, t1: float):
        self._ops.append({"kind": kind, "stage": self.stage_id,
                          "step": step, "mb": mb, "start": t0, "end": t1})

    def _block(self, tree):
        self._jax.tree_util.tree_leaves(tree)[0].block_until_ready()

    # ---- schedule ops (driver-dispatched, executed in strict order) ----
    def fwd(self, step: int, slot: int, mb: int, x, target=None,
            weight: float = 1.0):
        """Forward one microbatch through chunk ``slot``; the pullback
        (residuals) stays here.  Middle chunks return the (possibly
        int8-packed) activation slice; the last chunk its scalar loss."""
        from ray_tpu._private import chaos

        chaos.maybe_die("mpmd_fwd", self.stage_id)
        gc = self._global_chunk(slot)
        last = self._is_last_chunk(slot)
        t_in0 = time.time()
        xargs = self._wire_in(x, first=gc == 0)
        extra = ()
        if last:
            if target is None:
                raise ValueError("last chunk forward requires a target")
            extra = (self._to_batched(target),)
        t0 = time.time()
        y, vjp = self._fwd[slot](self.params[slot], *xargs, *extra)
        self._block(y)
        t1 = time.time()
        self._resid[(slot, mb)] = (vjp, float(weight), step)
        self._peak_inflight = max(self._peak_inflight, len(self._resid))
        self._record("X", step, mb, t_in0, t0)
        self._record("F", step, mb, t0, t1)
        if last:
            return float(self._to_host(y))
        out = self._wire_out(y)
        self._record("X", step, mb, t1, time.time())
        return out

    def bwd(self, step: int, slot: int, mb: int, dy=None):
        """Backward one microbatch on chunk ``slot``: consume the stored
        pullback, fold dparams into the chunk's accumulator, ship the
        input cotangent upstream (chunk 0 returns a token)."""
        from ray_tpu._private import chaos

        chaos.maybe_die("mpmd_bwd", self.stage_id)
        vjp, weight, fwd_step = self._resid.pop((slot, mb))
        if fwd_step != step:
            raise RuntimeError(
                f"stage {self.stage_id}: bwd(step={step}, slot={slot}, "
                f"mb={mb}) found residuals of step {fwd_step} — schedule "
                "corrupted")
        gc = self._global_chunk(slot)
        t_in0 = time.time()
        if dy is None:
            # Last chunk: d(loss)/d(loss), scaled by this microbatch's
            # weight (its true row share of the global batch) so ragged
            # microbatches accumulate EXACT full-batch gradients.
            dyargs = (self._jnp.asarray(weight, self._jnp.float32),)
        elif isinstance(dy, dict) and "q" in dy:
            dyargs = (self._to_batched(dy["q"]), self._to_batched(dy["s"]))
        else:
            dyargs = (self._to_batched(dy),)
        if self._acc[slot] is None:
            self._acc[slot] = self._zeros(self.params[slot])
        t0 = time.time()
        self._acc[slot], dx = self._bwd[slot](vjp, self._acc[slot], *dyargs)
        self._block(self._acc[slot])
        t1 = time.time()
        self._record("X", step, mb, t_in0, t0)
        self._record("B", step, mb, t0, t1)
        if gc == 0:
            return mb
        out = self._wire_out(dx)
        self._record("X", step, mb, t1, time.time())
        return out

    def apply_grads(self, scale: float = 1.0) -> dict:
        """Optimizer step on every owned chunk's accumulated grads;
        returns this step's observability payload."""
        from ray_tpu._private import chaos

        chaos.maybe_die("mpmd_apply", self.stage_id)
        if self._resid:
            raise RuntimeError(
                f"stage {self.stage_id}: apply with {len(self._resid)} "
                "unconsumed residuals — schedule corrupted")
        t0 = time.time()
        scale_dev = self._jnp.asarray(scale, self._jnp.float32)
        for slot in range(self.v):
            self.params[slot], self.opt_state[slot] = self._apply[slot](
                self.params[slot], self.opt_state[slot], self._acc[slot],
                scale_dev)
            self._acc[slot] = None
        self._block(self.params[0])
        t1 = time.time()
        self._step_count += 1
        self._record("A", self._step_count - 1, -1, t0, t1)
        out = self.stats()
        self._ops = []
        self._peak_inflight = 0
        return out

    def stats(self) -> dict:
        caches = {
            "fwd": sum(int(f._cache_size()) for f in self._fwd),
            "bwd": sum(int(f._cache_size()) for f in self._bwd),
            "apply": sum(int(f._cache_size()) for f in self._apply),
        }
        out = {
            "stage": self.stage_id,
            "rank": self.gang_rank,
            "steps": self._step_count,
            "peak_inflight": self._peak_inflight,
            "act_bytes": self._act_bytes,
            "wire_bytes": self._wire_bytes,
            "ops": list(self._ops),
            "busy_s": sum(o["end"] - o["start"] for o in self._ops
                          if o["kind"] in ("F", "B", "A")),
            "jit_cache": caches,
        }
        if self._zero_info[0] is not None:
            out["zero_opt_bytes_per_replica"] = sum(
                zi["zero_opt_bytes_per_replica"] for zi in self._zero_info)
            out["replicated_opt_bytes"] = sum(
                zi["replicated_opt_bytes"] for zi in self._zero_info)
        return out

    # ---- lifecycle / fault tolerance ----
    def reset(self):
        """Drop partial schedule state after a failed step — stale grad
        accumulations must not leak into the next optimizer update."""
        self._resid.clear()
        self._acc = [None] * self.v
        self._ops = []
        self._peak_inflight = 0
        return True

    def snapshot(self):
        """Host copy of (per-chunk params, per-chunk opt state, step).
        ZeRO-sharded opt state is all-gathered to replicated first
        (``zero.replicate_opt_state``) so every gang rank snapshots the
        same bytes and any rank's ref can restore any future rank."""
        params = [self._jax.tree_util.tree_map(self._to_host, p)
                  for p in self.params]
        opts = []
        for slot in range(self.v):
            opt = self.opt_state[slot]
            if self._zero[slot] is not None:
                from ray_tpu.parallel import zero as zero_mod

                opt = zero_mod.replicate_opt_state(opt, self._mesh)
            opts.append(self._jax.tree_util.tree_map(self._to_host, opt))
        return (params, opts, self._step_count)

    def restore(self, snap):
        params, opts, step_count = snap
        if not isinstance(params, list):  # single-chunk legacy snapshot
            params, opts = [params], [opts]
        for slot in range(self.v):
            self.params[slot] = self._put_repl(params[slot])
            if self._zero[slot] is not None:
                from ray_tpu.parallel import zero as zero_mod

                self.opt_state[slot] = zero_mod.place_opt_state(
                    opts[slot], self._mesh, self._zero[slot].opt_specs,
                    multihost=self.gang_size > 1)
            else:
                self.opt_state[slot] = self._put_repl(opts[slot])
        self._step_count = int(step_count)
        return True

    def get_params(self):
        """Host params; the per-chunk list for v > 1, the bare pytree for
        v == 1 (the pre-interleaving contract)."""
        out = [self._jax.tree_util.tree_map(self._to_host, p)
               for p in self.params]
        return out[0] if self.v == 1 else out


@ray_tpu.remote
class PipelineStage:
    """One single-process pipeline stage: a :class:`StageCore` behind an
    actor boundary (the ``gang_hosts=1`` deployment).  Methods execute
    in strict submission order — the actor is single-threaded — which is
    what makes the driver-side schedule an execution order."""

    def __init__(self, chunk_fns, chunk_params, optimizer=None, *,
                 stage_id: int = 0, num_stages: int = 1,
                 virtual_per_rank: int = 1, generation: int = 0,
                 wire_dtype: str = "fp32", wire_block: int = 256,
                 spmd_devices: int = 0, zero_sharding: str = "off",
                 restore_from: Any = None):
        import os

        from ray_tpu._private import chaos

        os.environ[chaos.GENERATION_ENV] = str(generation)
        if not isinstance(chunk_fns, (list, tuple)):
            chunk_fns, chunk_params = [chunk_fns], [chunk_params]
        self.core = StageCore(
            list(chunk_fns), list(chunk_params), optimizer,
            stage_id=stage_id, num_stages=num_stages,
            virtual_per_rank=virtual_per_rank, wire_dtype=wire_dtype,
            wire_block=wire_block, spmd_devices=spmd_devices,
            zero_sharding=zero_sharding, restore_from=restore_from)
        self.stage_id = self.core.stage_id

    def fwd(self, step, slot, mb, x, target=None, weight: float = 1.0):
        return self.core.fwd(step, slot, mb, x, target, weight)

    def bwd(self, step, slot, mb, dy=None):
        return self.core.bwd(step, slot, mb, dy)

    def apply_grads(self, scale: float = 1.0) -> dict:
        return self.core.apply_grads(scale)

    def stats(self) -> dict:
        return self.core.stats()

    def ping(self) -> int:
        return self.stage_id

    def reset(self):
        return self.core.reset()

    def snapshot(self):
        return self.core.snapshot()

    def restore(self, snap):
        return self.core.restore(snap)

    def get_params(self):
        return self.core.get_params()


# ---- gang-rank entry points (run inside MeshWorker.pipeline_step with
# the worker's state dict: importable module functions, never closures) ----

def _gang_stage_setup(state, kwargs: dict, restore_snap=None):
    from ray_tpu.parallel.mpmd_pipeline import StageCore

    state["mpmd_core"] = StageCore(restore_from=restore_snap, **kwargs)
    return True


def _gang_stage_op(state, op: str, *args, **kwargs):
    return getattr(state["mpmd_core"], op)(*args, **kwargs)


# ---------------------------------------------------------------------------
# Driver-side stage handles
# ---------------------------------------------------------------------------

class _SoloStage:
    """Driver handle for a single-actor stage (width 1)."""

    width = 1

    def __init__(self, actor):
        self.actor = actor

    def submit(self, op: str, per_rank_args: Sequence[tuple],
               **kwargs) -> List[Any]:
        return [getattr(self.actor, op).remote(*per_rank_args[0], **kwargs)]

    def ping_refs(self) -> List[Any]:
        return [self.actor.ping.remote()]

    def resync(self) -> None:
        pass  # solo actors have no sequence gate to clear

    def kill(self) -> None:
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass


class _GangStage:
    """Driver handle for a multi-host stage gang: every op is one gated
    ``MeshWorker.pipeline_step`` per rank at the next sequence position,
    so all ranks execute the identical op order — the property that
    keeps each rank's compiled collectives matched with its peers'."""

    def __init__(self, group: MeshGroup):
        self.group = group
        self.width = group.num_hosts
        self._seq = 0

    def submit(self, op: str, per_rank_args: Sequence[tuple],
               **kwargs) -> List[Any]:
        args_per_rank = [(_gang_stage_op, op) + tuple(a)
                         for a in per_rank_args]
        refs = self.group.submit_ordered(self._seq, args_per_rank,
                                         kwargs=kwargs)
        self._seq += 1
        return refs

    def setup(self, kwargs_base: dict, restore: Optional[List[Any]],
              timeout: float) -> None:
        self.group.seek_ranks(0)
        self._seq = 0
        per_rank = []
        for r in range(self.width):
            kw = dict(kwargs_base, gang_rank=r, gang_size=self.width)
            per_rank.append((_gang_stage_setup, kw,
                             None if restore is None else restore[r]))
        refs = self.group.submit_ordered(self._seq, per_rank)
        self._seq += 1
        gang_get(refs, timeout=timeout)

    def ping_refs(self) -> List[Any]:
        return [w.ping.remote() for w in self.group.workers]

    def resync(self) -> None:
        """Clear a poisoned sequence gate (a failed op fails every later
        queued op on its rank) so post-abort dispatch can resume."""
        self.group.seek_ranks(self._seq)

    def kill(self) -> None:
        try:
            self.group.shutdown()
        except Exception:
            pass


class _StepRec:
    """One submitted step: the host microbatches (for replay), the refs
    the driver drains, and bookkeeping flags.  ``aux_refs`` pins every
    intermediate activation/cotangent ref until the step drains —
    dropping them at dispatch would let ref-gc free a store-resident
    activation before its consumer stage resolved it."""
    __slots__ = ("idx", "xs", "ts", "weights", "loss_refs", "apply_refs",
                 "aux_refs", "snap", "drained", "trace_ctx")

    def __init__(self, idx, xs, ts, weights, snap):
        self.idx = idx
        self.xs = xs
        self.ts = ts
        self.weights = weights
        self.loss_refs: List[Any] = []
        self.apply_refs: List[Any] = []
        self.aux_refs: List[Any] = []
        self.snap = snap
        self.drained = False
        # One distributed trace per step (minted at dispatch, reused for
        # replay re-dispatch and the mpmd_stage_* spans at drain).
        self.trace_ctx = None


def _mpmd_metrics():
    """Lazy metric handles (internal_kv needs a connected driver)."""
    from ray_tpu.util.metrics import Counter, Gauge, Meter

    return {
        "bubble": Gauge("mpmd_bubble_fraction",
                        "1 - busy/(stages*wall) of the last drained step"),
        "steps": Counter("mpmd_steps_total", "pipeline train steps drained"),
        "replays": Counter("mpmd_replays_total",
                           "gang restarts absorbed by schedule replay"),
        "act_bytes": Meter("mpmd_activation_bytes",
                           "logical fp32 activation/cotangent bytes at "
                           "the stage boundaries"),
        "wire": Meter("mpmd_wire_bytes",
                      "activation/cotangent bytes actually shipped "
                      "through the object store (int8 wire shrinks "
                      "these ~4x vs mpmd_activation_bytes)"),
        "idle": Gauge("mpmd_stage_idle_frac",
                      "per-stage idle fraction of the last drained step",
                      tag_keys=("stage",)),
        "inflight": Gauge("mpmd_peak_inflight_microbatches",
                          "peak microbatches holding residuals on any "
                          "stage in the last drained step"),
    }


class MPMDPipeline:
    """Driver-side async (interleaved) 1F1B schedule over compiled stage
    actors or multi-host stage gangs.

    ``stage_fns``: ``num_stages * virtual_per_rank`` chunk callables in
    GLOBAL chunk order; the last must be ``loss_fn(params, x, target) ->
    scalar``.  Chunk c is owned by physical stage ``c % num_stages``
    (the interleaved assignment).  ``init_params``: per-chunk pytrees OR
    zero-arg factories (run on the stage).  ``stage_options``: per-stage
    StageCore kwargs (``spmd_devices``, ``zero_sharding``).

    3D composition knobs:

    - ``virtual_per_rank=v`` — interleaved virtual stages (v model
      chunks per physical stage; bubble shrinks toward ``1/(v*M)``).
    - ``wire_dtype="int8"`` — EQuARX block-scaled int8 activations AND
      cotangents on the inter-stage wire (~4x fewer bytes; fp32 is the
      bit-stable default).
    - ``gang_hosts=G`` — every stage becomes a G-process MeshGroup gang
      forming one jax.distributed SPMD world (with
      ``gang_local_device_count`` virtual/real devices per process);
      microbatches shard across the whole gang and ZeRO shards the
      optimizer across every gang device.

    Lockstep use (drop-in)::

        pipe = MPMDPipeline([f0, loss_fn], [p0, p1], num_microbatches=4)
        loss = pipe.train_step(x, t)        # one blocking sync per step

    Streaming use (the zero-sync hot path)::

        for x, t in batches:
            pipe.submit_step(x, t)          # <= step_window in flight
        losses = pipe.flush()               # [(step_idx, loss), ...]

    Fault tolerance: ``max_restarts > 0`` arms snapshotting (every
    ``snapshot_interval`` steps, store-resident) and replay — a stage or
    gang-rank death respawns every stage from the latest confirmed
    snapshot and re-dispatches every step since, in order."""

    def __init__(self, stage_fns: Sequence[Callable],
                 init_params: Sequence[Any], optimizer=None,
                 num_microbatches: int = 4,
                 stage_options: Optional[List[dict]] = None, *,
                 schedule: str = "1f1b", virtual_per_rank: int = 1,
                 wire_dtype: str = "fp32", wire_block: int = 256,
                 gang_hosts: int = 1, gang_platform: Optional[str] = None,
                 gang_local_device_count: Optional[int] = None,
                 step_window: int = 2, max_restarts: int = 0,
                 snapshot_interval: int = 1,
                 drain_timeout: Optional[float] = None,
                 bootstrap_timeout: float = 180.0,
                 export_metrics: bool = True):
        v = max(1, int(virtual_per_rank))
        if len(stage_fns) % v != 0:
            raise ValueError(
                f"{len(stage_fns)} chunk fns do not tile "
                f"virtual_per_rank={v}")
        n = len(stage_fns) // v
        if len(init_params) != len(stage_fns):
            raise ValueError("one params pytree per chunk fn")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"schedule must be 1f1b|gpipe, got {schedule!r}")
        if v > 1 and int(num_microbatches) % n != 0:
            raise ValueError(
                f"interleaved schedule needs num_microbatches divisible "
                f"by num_stages ({num_microbatches} % {n} != 0)")
        if wire_dtype not in ("fp32", "int8"):
            raise ValueError(f"wire_dtype must be fp32|int8, "
                             f"got {wire_dtype!r}")
        self.num_stages = n
        self.virtual_per_rank = v
        self.num_chunks = n * v
        self.num_microbatches = int(num_microbatches)
        self.schedule = schedule
        self.wire_dtype = wire_dtype
        self.wire_block = int(wire_block)
        self.gang_hosts = max(1, int(gang_hosts))
        self.gang_platform = gang_platform
        self.gang_local_device_count = gang_local_device_count
        # The MeshGroup deployment also serves gang_hosts=1 when the
        # stage processes need a platform/device-count bootstrap BEFORE
        # their first jax import (virtual devices for intra-stage SPMD
        # on boxes whose env doesn't pre-set XLA flags).
        self._use_gang = (self.gang_hosts > 1 or gang_platform is not None
                          or gang_local_device_count is not None)
        self.step_window = max(1, int(step_window))
        self.max_restarts = int(max_restarts)
        self.snapshot_interval = max(1, int(snapshot_interval))
        self.drain_timeout = drain_timeout
        self.bootstrap_timeout = bootstrap_timeout
        self.restart_count = 0
        self._stage_fns = list(stage_fns)
        self._init_params = list(init_params)
        self._optimizer = optimizer
        self._stage_opts = list(stage_options or [{} for _ in range(n)])
        if len(self._stage_opts) != n:
            raise ValueError(f"stage_options must have one entry per "
                             f"PHYSICAL stage ({n}), got "
                             f"{len(self._stage_opts)}")
        self._generation = 0
        self.stages: List[Any] = []       # solo actor handles (width 1)
        self._gangs: List[MeshGroup] = []  # stage gangs (width > 1)
        self._handles: List[Any] = []
        self._spawn_stages(restore_refs=None)

        self._window: InflightWindow = InflightWindow(self.step_window)
        self._replay: collections.deque = collections.deque()  # _StepRec
        self._results: List[tuple] = []
        self._next_idx = 0
        self._snap: Optional[tuple] = None          # (idx, [[refs]/stage])
        self._pending_snap: Optional[tuple] = None
        self._last_report: Optional[dict] = None
        self._act_bytes_total = 0
        self._wire_bytes_total = 0
        self._busy_total = 0.0
        self._wall_total = 0.0
        self._peak_window = 0
        self._metrics = None
        if export_metrics:
            try:
                self._metrics = _mpmd_metrics()
            except Exception:
                self._metrics = None

    # ---- stage fn / param assignment ----
    def _chunks_of(self, k: int) -> List[int]:
        return [slot * self.num_stages + k
                for slot in range(self.virtual_per_rank)]

    def _stage_kwargs(self, k: int) -> dict:
        return dict(
            stage_id=k, num_stages=self.num_stages,
            virtual_per_rank=self.virtual_per_rank,
            wire_dtype=self.wire_dtype, wire_block=self.wire_block,
            **self._stage_opts[k])

    # ---- gang lifecycle ----
    def _spawn_stages(self, restore_refs) -> None:
        n = self.num_stages
        fns = [[self._stage_fns[c] for c in self._chunks_of(k)]
               for k in range(n)]
        params = [[self._init_params[c] for c in self._chunks_of(k)]
                  for k in range(n)]
        if not self._use_gang:
            self.stages = [
                PipelineStage.remote(
                    fns[k], params[k], self._optimizer,
                    generation=self._generation,
                    restore_from=None if restore_refs is None
                    else restore_refs[k][0],
                    **self._stage_kwargs(k))
                for k in range(n)
            ]
            self._handles = [_SoloStage(a) for a in self.stages]
            return
        # Multi-host: one MeshGroup gang per stage.  Spawn every gang
        # first (placement + jax.distributed rendezvous are the slow
        # part and independent), then fan the setups out.
        self.stages = []
        self._gangs = [
            MeshGroup(self.gang_hosts, platform=self.gang_platform,
                      local_device_count=self.gang_local_device_count,
                      bootstrap_timeout=self.bootstrap_timeout)
            for _ in range(n)
        ]
        self._handles = [_GangStage(g) for g in self._gangs]
        for k, h in enumerate(self._handles):
            kw = dict(self._stage_kwargs(k), chunk_fns=fns[k],
                      chunk_params=params[k], optimizer=self._optimizer)
            h.setup(kw, None if restore_refs is None else restore_refs[k],
                    timeout=self.bootstrap_timeout)

    def _teardown_stages(self) -> None:
        for h in self._handles:
            h.kill()
        self.stages = []
        self._gangs = []
        self._handles = []

    def _dead_stages(self, deadline: float = 15.0) -> List[int]:
        """Bounded ping fan-out over every rank of every stage; returns
        the stage ids with any dead/unresponsive rank."""
        refs, owner = [], []
        for k, h in enumerate(self._handles):
            for r in h.ping_refs():
                refs.append(r)
                owner.append(k)
        try:
            gang_get(refs, timeout=deadline)
            return []
        except exc.MeshGroupError as e:
            return sorted({owner[i] for i in e.failed_ranks})
        except Exception:
            return list(range(self.num_stages))

    # ---- batch slicing ----
    def _rank_split(self, arr: np.ndarray, width: int) -> List[np.ndarray]:
        if width == 1:
            return [arr]
        return np.split(arr, width)

    # ---- schedule dispatch (pure ref wiring — no tensors, no waits) ----
    def _dispatch_step(self, rec: _StepRec) -> None:
        from ray_tpu import observability as obs

        minted = False
        if rec.trace_ctx is None and obs.enabled():
            # Join the caller's trace when one is live (e.g. a learner
            # update_async boundary); mint a fresh per-step root else.
            rec.trace_ctx = obs.get_context()
            if rec.trace_ctx is None:
                rec.trace_ctx = obs.mint_context()
                minted = True
        if rec.trace_ctx is not None:
            # Dispatch inside the step's trace: every stage-actor submit
            # below inherits it, so one training step assembles into one
            # cross-process timeline.
            import time as _time

            from ray_tpu._private import profiling

            t0 = _time.perf_counter()
            with obs.use_context(rec.trace_ctx):
                self._dispatch_step_inner(rec)
            # A freshly minted step records its dispatch AS the trace
            # root: the stage actors' execute spans parent to the root
            # id, and flow arrows need that span to exist.
            profiling.record_span("mpmd_step_dispatch", t0,
                                  _time.perf_counter(), step=rec.idx,
                                  _trace_ctx=rec.trace_ctx, _root=minted)
            return
        self._dispatch_step_inner(rec)

    def _dispatch_step_inner(self, rec: _StepRec) -> None:
        if rec.snap:
            refs = [h.submit("snapshot", [() for _ in range(h.width)])
                    for h in self._handles]
            self._pending_snap = (rec.idx, refs)
        S, M, v = self.num_stages, len(rec.xs), self.virtual_per_rank
        C = self.num_chunks
        queues = [collections.deque(
            stage_schedule(self.schedule, S, M, k, v)) for k in range(S)]
        acts: Dict[tuple, List[Any]] = {}
        cots: Dict[tuple, List[Any]] = {}
        classic = self.schedule == "1f1b" and v == 1
        window = InflightWindow(S if classic else M)
        rec.loss_refs, rec.apply_refs = [], []
        aux: List[Any] = []
        remaining = sum(len(q) for q in queues)
        while remaining:
            progressed = False
            for k in range(S):
                q = queues[k]
                h = self._handles[k]
                while q:
                    op, c, m = q[0]
                    slot = c // S
                    if op == "F":
                        if c == 0:
                            srcs = self._rank_split(rec.xs[m], h.width)
                        else:
                            srcs = acts.get((c - 1, m))
                            if srcs is None:
                                break
                        if c == 0:
                            window.append(m)
                            self._peak_window = max(self._peak_window,
                                                    len(window))
                            if classic and window.over_depth:
                                raise RuntimeError(
                                    "1F1B scheduler admitted more than "
                                    f"{window.depth} microbatches")
                        if c == C - 1:
                            tgt = self._rank_split(rec.ts[m], h.width)
                            refs = h.submit(
                                "fwd",
                                [(rec.idx, slot, m, srcs[r], tgt[r],
                                  float(rec.weights[m]))
                                 for r in range(h.width)])
                            rec.loss_refs.append(refs[0])
                            aux += refs[1:]
                        else:
                            refs = h.submit(
                                "fwd",
                                [(rec.idx, slot, m, srcs[r])
                                 for r in range(h.width)])
                            acts[(c, m)] = refs
                    else:  # "B"
                        if c == C - 1:
                            dys: Optional[List[Any]] = None
                        else:
                            dys = cots.get((c + 1, m))
                            if dys is None:
                                break
                        if c == 0:
                            window.remove(m)
                        refs = h.submit(
                            "bwd",
                            [(rec.idx, slot, m,
                              None if dys is None else dys[r])
                             for r in range(h.width)])
                        cots[(c, m)] = refs
                    q.popleft()
                    remaining -= 1
                    progressed = True
            if not progressed:
                raise RuntimeError(
                    f"{self.schedule} schedule deadlocked with "
                    f"{remaining} ops pending (S={S}, M={M}, v={v})")
        for h in self._handles:
            rec.apply_refs += h.submit("apply_grads",
                                       [() for _ in range(h.width)])
        rec.aux_refs = aux + [r for refs in list(acts.values())
                              + list(cots.values()) for r in refs]

    def _split_batch(self, x, target):
        M = self.num_microbatches
        if len(x) < M:
            raise ValueError(
                f"batch of {len(x)} rows cannot fill num_microbatches={M} "
                "(an empty microbatch means a NaN loss, not an error)")
        if len(x) != len(target):
            raise ValueError("x and target row counts differ")
        width = self._handles[0].width if self._handles else 1
        if width > 1 and len(x) % (M * width) != 0:
            raise ValueError(
                f"gang mode needs batch % (num_microbatches * gang_hosts) "
                f"== 0 so every rank gets an equal slice; got "
                f"{len(x)} % ({M} * {width}) != 0")
        xs = np.array_split(x, M)
        ts = np.array_split(target, M)
        # True per-microbatch weights: grad accumulation and the reported
        # loss weight each microbatch by its ACTUAL row share, so ragged
        # splits (len(x) % M != 0) match the single-process full-batch
        # gradients exactly.
        weights = np.asarray([len(xb) for xb in xs], np.float64) / len(x)
        return xs, ts, weights

    # ---- streaming API (the zero-sync hot path) ----
    def submit_step(self, x: np.ndarray, target: np.ndarray) -> int:
        """Dispatch one full schedule asynchronously; blocks (draining
        the oldest step) only once more than ``step_window`` steps are in
        flight.  Returns the step index."""
        xs, ts, weights = self._split_batch(x, target)
        idx = self._next_idx
        self._next_idx += 1
        snap = self.max_restarts > 0 and (
            self._snap is None and self._pending_snap is None
            or (self._pending_snap is None
                and idx - self._snap[0] >= self.snapshot_interval))
        rec = _StepRec(idx, xs, ts, weights, snap)
        self._dispatch_step(rec)
        self._replay.append(rec)
        self._window.append(rec)
        while self._window.over_depth:
            self._drain_one()
        return idx

    def flush(self) -> List[tuple]:
        """Drain every in-flight step; returns all accumulated
        ``(step_idx, loss)`` pairs (destructive read)."""
        while self._window:
            self._drain_one()
        out, self._results = self._results, []
        return out

    def train_step(self, x: np.ndarray, target: np.ndarray) -> float:
        """Lockstep step (compat API): submit + drain everything, return
        THIS step's weighted mean microbatch loss."""
        _note_sync()
        idx = self.submit_step(x, target)
        drained = dict(self.flush())
        return drained[idx]

    # ---- drain + recovery ----
    def _drain_one(self) -> None:
        rec = self._window.peek()
        while True:
            try:
                vals = gang_get(rec.loss_refs + rec.apply_refs,
                                timeout=self.drain_timeout)
                break
            except exc.MeshGroupError as e:
                self._recover(e)
            except exc.RayTpuError:
                # A user exception — or a task poisoned by an upstream
                # stage death (surfaces as a TaskError, not an actor
                # error).  Disambiguate with a bounded ping fan-out.
                dead = self._dead_stages()
                if dead:
                    self._recover(exc.MeshGroupError(
                        f"pipeline stage(s) {dead} died mid-step",
                        failed_ranks={d: exc.ActorDiedError(
                            f"stage {d} unresponsive") for d in dead}))
                    continue
                self._abort()
                raise
        M = len(rec.loss_refs)
        losses, rank_stats = vals[:M], vals[M:]
        loss = float(np.dot(rec.weights, np.asarray(losses, np.float64)))
        self._window.popleft()
        rec.drained = True
        rec.aux_refs = []  # consumers finished: release the pins
        self._results.append((rec.idx, loss))
        self._ingest_stats(rec, rank_stats)
        # Snapshot confirmation: this step drained, so every op queued
        # before it — including the snapshot — executed.
        if self._pending_snap is not None and \
                rec.idx >= self._pending_snap[0]:
            self._snap = self._pending_snap
            self._pending_snap = None
            while self._replay and self._replay[0].idx < self._snap[0]:
                self._replay.popleft()
        elif self.max_restarts == 0:
            while self._replay and self._replay[0].drained:
                self._replay.popleft()

    def _recover(self, cause: exc.MeshGroupError) -> None:
        """All-or-nothing gang restart + in-order schedule replay."""
        from ray_tpu import observability as obs

        obs.flight_record(f"mpmd_gang_restart: {cause}")
        if self.restart_count >= self.max_restarts:
            cause.restarts = self.restart_count
            self._abort(teardown=False)
            raise cause
        self.restart_count += 1
        self._generation += 1
        self._teardown_stages()
        # The fresh stage gangs resolve these snapshot refs concurrently
        # during setup — a cooperative striped broadcast on the transfer
        # plane, so restart time doesn't grow with gang width.
        restore = [list(refs) for refs in self._snap[1]] \
            if self._snap is not None else None
        self._pending_snap = None  # its refs died with the old gang
        self._spawn_stages(restore_refs=restore)
        for rec in self._replay:
            if rec.snap and self._snap is not None \
                    and rec.idx <= self._snap[0]:
                rec.snap = False  # already restored from this snapshot
            self._dispatch_step(rec)
        if self._metrics is not None:
            try:
                self._metrics["replays"].inc()
            except Exception:
                pass

    def _abort(self, teardown: bool = False) -> None:
        """Drop in-flight schedule state after an unrecoverable error so
        a retry doesn't double-apply; stages reset their accumulators."""
        self._window.clear()
        self._replay.clear()
        self._pending_snap = None
        if teardown:
            self._teardown_stages()
            return
        for h in self._handles:
            try:
                h.resync()
                gang_get(h.submit("reset", [() for _ in range(h.width)]),
                         timeout=30.0)
            except Exception:
                pass

    # ---- observability ----
    def _merge_rank_stats(self, rank_stats: Sequence[dict]) -> List[dict]:
        """Fold per-rank apply payloads into one dict per stage: rank 0
        carries the spans/watermarks (ranks run the identical schedule),
        boundary bytes sum across ranks (each ships its own slice)."""
        width = self._handles[0].width if self._handles else 1
        out = []
        for k in range(self.num_stages):
            group = list(rank_stats[k * width:(k + 1) * width])
            st = dict(group[0])
            st["act_bytes"] = sum(g["act_bytes"] for g in group)
            st["wire_bytes"] = sum(g["wire_bytes"] for g in group)
            out.append(st)
        return out

    def _ingest_stats(self, rec: _StepRec, rank_stats: Sequence[dict]):
        try:
            stage_stats = self._merge_rank_stats(rank_stats)
            ops = [o for st in stage_stats for o in st["ops"]]
            wall = (max(o["end"] for o in ops)
                    - min(o["start"] for o in ops)) if ops else 0.0
            busy = [st["busy_s"] for st in stage_stats]
            bubble = 1.0 - sum(busy) / (self.num_stages * wall) \
                if wall > 0 else 0.0
            act_bytes = sum(st["act_bytes"] for st in stage_stats) \
                - self._act_bytes_total
            wire_bytes = sum(st["wire_bytes"] for st in stage_stats) \
                - self._wire_bytes_total
            self._act_bytes_total += act_bytes
            self._wire_bytes_total += wire_bytes
            self._busy_total += sum(busy)
            self._wall_total += wall
            self._last_report = {
                "step": rec.idx,
                "bubble_fraction": bubble,
                "wall_s": wall,
                "busy_s": busy,
                "peak_inflight": {st["stage"]: st["peak_inflight"]
                                  for st in stage_stats},
                "jit_cache": {st["stage"]: st["jit_cache"]
                              for st in stage_stats},
                "act_bytes": act_bytes,
                "wire_bytes": wire_bytes,
                "ops": {st["stage"]: st["ops"] for st in stage_stats},
            }
            from ray_tpu._private import profiling

            for o in ops:
                profiling.record_span(
                    {"F": "mpmd_stage_fwd", "B": "mpmd_stage_bwd",
                     "A": "mpmd_stage_apply", "X": "mpmd_stage_transfer"}
                    [o["kind"]], o["start"], o["end"], stage=o["stage"],
                    step=o["step"], mb=o["mb"], _trace_ctx=rec.trace_ctx)
            if self._metrics is not None:
                m = self._metrics
                m["bubble"].set(bubble)
                m["steps"].inc()
                m["act_bytes"].mark(float(act_bytes))
                m["wire"].mark(float(wire_bytes))
                m["inflight"].set(float(max(
                    st["peak_inflight"] for st in stage_stats)))
                for st, b in zip(stage_stats, busy):
                    idle = 1.0 - b / wall if wall > 0 else 0.0
                    m["idle"].set(idle, tags={"stage": str(st["stage"])})
        except Exception:
            pass  # observability is best-effort, never the step path

    def last_step_report(self) -> Optional[dict]:
        """Observability payload of the most recently drained step."""
        return self._last_report

    def stats(self) -> dict:
        rep = self._last_report or {}
        return {
            "num_stages": self.num_stages,
            "virtual_per_rank": self.virtual_per_rank,
            "num_microbatches": self.num_microbatches,
            "schedule": self.schedule,
            "wire_dtype": self.wire_dtype,
            "gang_hosts": self.gang_hosts,
            "steps_submitted": self._next_idx,
            "steps_inflight": len(self._window),
            "restarts": self.restart_count,
            "bubble_fraction": rep.get("bubble_fraction"),
            "peak_inflight": rep.get("peak_inflight"),
            "jit_cache": rep.get("jit_cache"),
            "activation_bytes": self._act_bytes_total,
            "wire_bytes": self._wire_bytes_total,
            "wire_reduction_vs_fp32": (
                self._act_bytes_total / self._wire_bytes_total
                if self._wire_bytes_total else 1.0),
            "act_gb_per_s": (self._act_bytes_total / self._wall_total / 1e9
                             if self._wall_total > 0 else 0.0),
            "driver_peak_window": self._peak_window,
        }

    # ---- params access (lockstep paths) ----
    def get_params(self) -> List[Any]:
        """Host params per GLOBAL chunk (length ``num_stages * v``; for
        v=1 that is the familiar one-pytree-per-stage list).  Gang mode
        reads rank 0 (params are replicated across the gang)."""
        _note_sync()
        self.flush()
        per_stage = gang_get(
            [h.submit("get_params", [() for _ in range(h.width)])[0]
             for h in self._handles])
        out = []
        for c in range(self.num_chunks):
            k, slot = c % self.num_stages, c // self.num_stages
            got = per_stage[k]
            out.append(got[slot] if self.virtual_per_rank > 1 else got)
        return out

    def stop(self):
        try:
            if self._window:
                self.flush()
        except Exception:
            pass
        self._teardown_stages()

    def __enter__(self) -> "MPMDPipeline":
        return self

    def __exit__(self, exc_type, exc_val, tb) -> None:
        if exc_type is not None:
            self._abort(teardown=True)
        else:
            self.stop()
