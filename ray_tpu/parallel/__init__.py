"""TPU-native parallelism layer.

This is the framework's replacement for the reference's entire GPU
communication stack — ray.util.collective NCCL groups
(python/ray/util/collective/collective_group/nccl_collective_group.py:127),
Torch DDP process groups (python/ray/train/torch/config.py:69) and the
multi-GPU tower logic in RLlib (rllib/execution/train_ops.py:82).  On TPU
none of that exists as a library: communication is *in the compiled
program* — XLA collectives (psum/all_gather/ppermute/all_to_all) over ICI,
placed by sharding annotations on a jax.sharding.Mesh.  What this package
provides instead:

- MeshSpec / make_mesh: named logical axes {data, fsdp, model, expert,
  sequence, pipe} over real or virtual devices,
- sharding rules: logical-axis → mesh-axis mapping and helpers,
- ring attention + Ulysses all-to-all sequence parallelism (shard_map),
- pipeline parallelism with microbatching (shard_map + ppermute),
- MeshGroup: the gang-scheduled actor group that *hosts* a multi-host mesh
  (the TPU equivalent of Train's worker-group + process-group bootstrap).
"""
from ray_tpu.parallel.mesh import MeshSpec, make_mesh, local_mesh  # noqa: F401
from ray_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    batch_sharding,
    replicated,
    shard_params,
)
from ray_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
from ray_tpu.parallel.flow import (  # noqa: F401
    CancellationToken,
    RefStream,
    Stage,
    Window,
)
from ray_tpu.parallel.mesh_group import (  # noqa: F401
    MeshGroup,
    StepPipeline,
    bootstrap_jax_distributed,
    driver_sync_count,
    gang_get,
    is_transport_abort,
    rendezvous,
)


def __getattr__(name):
    # mpmd_pipeline spawns actors on import-site use; keep it lazy so
    # `import ray_tpu.parallel` stays runtime-free.  elastic.py pulls in
    # jax/optax at import time — lazy for the same reason.
    if name in ("MPMDPipeline", "PipelineStage", "StageCore",
                "mpmd_driver_sync_count", "stage_schedule",
                "simulate_schedule"):
        from ray_tpu.parallel import mpmd_pipeline

        return getattr(mpmd_pipeline, name)
    if name in ("ElasticMeshGroup", "LocalElastic", "build_elastic_step",
                "reference_trajectory"):
        from ray_tpu.parallel import elastic

        return getattr(elastic, name)
    raise AttributeError(name)
