"""Tune-equivalent hyperparameter tuning (reference: python/ray/tune/)."""
from ray_tpu.tune.schedulers import (  # noqa: F401
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search.tpe import Searcher, TPESearch  # noqa: F401
from ray_tpu.tune.search.sample import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.trial import Trial  # noqa: F401
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner, run  # noqa: F401

ASHAScheduler = AsyncHyperBandScheduler
