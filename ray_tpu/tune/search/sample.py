"""Search-space primitives (reference: ray.tune.search.sample + grid_search
marker in python/ray/tune/search/variant_generator.py)."""
from __future__ import annotations

import random
from typing import Any, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class Randint(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}
