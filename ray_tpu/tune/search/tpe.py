"""Native TPE (tree-structured Parzen estimator) searcher — the
Bayesian-optimization-class search algorithm.

Reference surface: ray.tune.search.Searcher (searcher.py:41, the
suggest/on_trial_complete contract) and the BayesOpt/HyperOpt wrappers
(python/ray/tune/search/hyperopt/hyperopt_search.py) — the reference
delegates the actual model to external libraries; here the estimator is
implemented directly (numpy only), per Bergstra et al., "Algorithms for
Hyper-Parameter Optimization" (NeurIPS 2011):

- split observed configs into good/bad by a metric quantile (gamma),
- model each as a Parzen window (per-dimension KDE / smoothed categorical),
- sample candidates from the good model l(x) and keep the candidate
  maximizing l(x)/g(x).

Dimensions are modeled independently (the "tree" is flat here — nested
search spaces flatten to paths), which matches hyperopt's default.
"""
from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.basic_variant import _set_path, _split_space
from ray_tpu.tune.search.sample import Choice, Domain, LogUniform, Randint, \
    Uniform


class Searcher:
    """Feedback-driven config suggestion (reference: searcher.py:41)."""

    metric: Optional[str] = None
    mode: Optional[str] = None

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]):
        # Fill only what the searcher's constructor left unset — never
        # clobber an explicit metric/mode (the reference contract refuses
        # overwrites of already-set properties).
        if metric and self.metric is None:
            self.metric = metric
        if mode and self.mode is None:
            self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None):
        pass

    def add_evaluated_point(self, config: Dict[str, Any],
                            result: Dict[str, Any]):
        """Feed a finished (config, result) pair from outside the
        suggest flow — used by experiment resume to re-arm the model."""


def _flatten(space: Dict[str, Any]) -> List[Tuple[tuple, Any]]:
    """Leaves of the search space as (path, domain-or-const); grid axes
    degrade to categorical choices under TPE.  Built on the same traversal
    the variant generator uses so the two cannot drift."""
    out = []
    for path, (kind, v) in _split_space(space or {}):
        out.append((path, Choice(v) if kind == "grid" else v))
    return out


class _NumericDim:
    """Parzen window over a (possibly log- or integer-) numeric domain."""

    def __init__(self, domain: Domain):
        self.domain = domain
        if isinstance(domain, LogUniform):
            self.lo, self.hi, self.log, self.int = domain.lo, domain.hi, \
                True, False
        elif isinstance(domain, Uniform):
            self.lo, self.hi, self.log, self.int = domain.low, domain.high, \
                False, False
        elif isinstance(domain, Randint):
            self.lo, self.hi, self.log, self.int = domain.low, \
                domain.high - 1, False, True
        else:
            raise TypeError(domain)

    def to_unit(self, value: float) -> float:
        v = math.log(value) if self.log else float(value)
        return (v - self.lo) / max(self.hi - self.lo, 1e-12)

    def from_unit(self, u: float):
        v = self.lo + u * (self.hi - self.lo)
        v = math.exp(v) if self.log else v
        return int(round(v)) if self.int else v

    def sample_kde(self, rng: np.random.Generator,
                   obs: np.ndarray, n: int) -> np.ndarray:
        """Draw from a Parzen window over unit-space observations."""
        if obs.size == 0:
            return rng.uniform(0.0, 1.0, size=n)
        # Scott-ish bandwidth, floored so early rounds keep exploring.
        bw = max(obs.std() * (obs.size ** -0.2), 0.08)
        centers = obs[rng.integers(0, obs.size, size=n)]
        return np.clip(centers + rng.normal(0.0, bw, size=n), 0.0, 1.0)

    @staticmethod
    def logpdf(x: np.ndarray, obs: np.ndarray) -> np.ndarray:
        """log Parzen density of x under observations (unit space)."""
        if obs.size == 0:
            return np.zeros_like(x)  # uniform on [0,1]
        bw = max(obs.std() * (obs.size ** -0.2), 0.08)
        d = (x[:, None] - obs[None, :]) / bw
        comp = -0.5 * d * d - math.log(bw * math.sqrt(2 * math.pi))
        return np.logaddexp.reduce(comp, axis=1) - math.log(obs.size)


class TPESearch(Searcher):
    def __init__(self, space: Dict[str, Any],
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 n_initial_points: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        self.metric, self.mode = metric, mode
        self.gamma = gamma
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self._rng = np.random.default_rng(seed)
        self._pyrng = random.Random(seed)
        self._dims: List[Tuple[tuple, Any]] = _flatten(space or {})
        self._live: Dict[str, Dict[str, Any]] = {}
        # Completed observations: (flat unit/categorical values, score).
        self._obs: List[Tuple[Dict[tuple, Any], float]] = []

    # ---- suggest ----
    def suggest(self, trial_id: str) -> Dict[str, Any]:
        flat: Dict[tuple, Any] = {}
        if len(self._obs) < self.n_initial:
            for path, dom in self._dims:
                flat[path] = dom.sample(self._pyrng) \
                    if isinstance(dom, Domain) else dom
        else:
            good, bad = self._split()
            for path, dom in self._dims:
                if not isinstance(dom, Domain):
                    flat[path] = dom
                elif isinstance(dom, Choice):
                    flat[path] = self._suggest_choice(dom, path, good, bad)
                else:
                    flat[path] = self._suggest_numeric(dom, path, good, bad)
        self._live[trial_id] = {"flat": flat}
        cfg: Dict[str, Any] = {}
        for path, v in flat.items():
            _set_path(cfg, path, v)
        return cfg

    def _split(self):
        scores = np.array([s for _, s in self._obs])
        n_good = max(1, int(math.ceil(self.gamma * len(scores))))
        order = np.argsort(-scores)  # maximize internal score
        good_idx = set(order[:n_good].tolist())
        good = [self._obs[i][0] for i in range(len(self._obs))
                if i in good_idx]
        bad = [self._obs[i][0] for i in range(len(self._obs))
               if i not in good_idx]
        return good, bad

    def _suggest_numeric(self, dom, path, good, bad):
        nd = _NumericDim(dom)
        g = np.array([nd.to_unit(o[path]) for o in good if path in o])
        b = np.array([nd.to_unit(o[path]) for o in bad if path in o])
        cand = nd.sample_kde(self._rng, g, self.n_candidates)
        ei = nd.logpdf(cand, g) - nd.logpdf(cand, b)
        return nd.from_unit(float(cand[int(np.argmax(ei))]))

    def _suggest_choice(self, dom: Choice, path, good, bad):
        cats = dom.categories

        def weights(obs_list):
            w = np.ones(len(cats))  # Laplace smoothing
            for o in obs_list:
                if path in o:
                    try:
                        w[cats.index(o[path])] += 1.0
                    except ValueError:
                        pass
            return w / w.sum()

        ratio = weights(good) / weights(bad)
        cand_idx = self._rng.choice(
            len(cats), size=min(self.n_candidates, len(cats)),
            p=weights(good), replace=True)
        best = max(cand_idx.tolist(), key=lambda i: ratio[i])
        return cats[best]

    # ---- feedback ----
    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None):
        live = self._live.pop(trial_id, None)
        if live is None or not result or self.metric not in result:
            return
        self._record(live["flat"], float(result[self.metric]))

    def _record(self, flat: Dict[tuple, Any], score: float):
        if (self.mode or "max") == "min":
            score = -score
        self._obs.append((flat, score))

    def add_evaluated_point(self, config: Dict[str, Any],
                            result: Dict[str, Any]):
        if not result or self.metric not in result:
            return
        flat: Dict[tuple, Any] = {}

        def walk(d, prefix=()):
            for k, v in d.items():
                if isinstance(v, dict):
                    walk(v, prefix + (k,))
                else:
                    flat[prefix + (k,)] = v

        walk(config)
        self._record(flat, float(result[self.metric]))
