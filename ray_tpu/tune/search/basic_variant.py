"""Grid/random variant generation (reference: python/ray/tune/search/
basic_variant.py + variant_generator.py)."""
from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List

from ray_tpu.tune.search.sample import Domain


def _split_space(space: Dict[str, Any], prefix=()):
    """Yield (path, spec) leaves; dicts recurse."""
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict) and "grid_search" in v and len(v) == 1:
            yield path, ("grid", v["grid_search"])
        elif isinstance(v, dict):
            yield from _split_space(v, path)
        elif isinstance(v, Domain):
            yield path, ("sample", v)
        else:
            yield path, ("const", v)


def _set_path(d: dict, path, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def generate_variants(space: Dict[str, Any], num_samples: int = 1,
                      seed: int | None = None) -> Iterator[Dict[str, Any]]:
    """Cross product of grid axes × num_samples draws of stochastic axes."""
    rng = random.Random(seed)
    leaves = list(_split_space(space or {}))
    grid_axes = [(p, vals) for p, (kind, vals) in leaves if kind == "grid"]
    grids = itertools.product(*[vals for _, vals in grid_axes]) \
        if grid_axes else [()]
    for grid_combo in grids:
        for _ in range(num_samples):
            cfg: Dict[str, Any] = {}
            for (p, (kind, v)) in leaves:
                if kind == "const":
                    _set_path(cfg, p, v)
                elif kind == "sample":
                    _set_path(cfg, p, v.sample(rng))
            for (p, _), val in zip(grid_axes, grid_combo):
                _set_path(cfg, p, val)
            yield cfg


class BasicVariantGenerator:
    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: int | None = None):
        self._variants: List[Dict[str, Any]] = list(
            generate_variants(space, num_samples, seed))

    def __iter__(self):
        return iter(self._variants)

    def __len__(self):
        return len(self._variants)
