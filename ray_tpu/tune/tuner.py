"""Tuner / tune.run / ResultGrid (reference: python/ray/tune/tuner.py:44,
tune/tune.py:164, tune/result_grid.py)."""
from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.result import Result
from ray_tpu.tune.execution.trial_runner import TrialRunner
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.trial import ERROR, TERMINATED, Trial


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Any] = None  # BasicVariantGenerator default
    seed: Optional[int] = None


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str = "max"):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self.trials)

    def __getitem__(self, i) -> Result:
        t = self.trials[i]
        return Result(metrics=t.last_result, checkpoint=t.checkpoint,
                      error=t.error, metrics_history=t.metrics_history)

    @property
    def errors(self) -> List[BaseException]:
        return [t.error for t in self.trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required")
        sign = 1 if mode == "max" else -1
        done = [t for t in self.trials if t.last_result.get(metric) is not None]
        if not done:
            raise ValueError("no trial reported the metric")
        best = max(done, key=lambda t: sign * t.last_result[metric])
        return Result(metrics=best.last_result, checkpoint=best.checkpoint,
                      metrics_history=best.metrics_history)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([{"trial_id": t.id, **t.config, **t.last_result}
                             for t in self.trials])


class Tuner:
    def __init__(self, trainable: Callable,
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None):
        # Trainers (BaseTrainer) are adapted via as_trainable().
        from ray_tpu.train.base_trainer import BaseTrainer

        if isinstance(trainable, BaseTrainer):
            trainable = trainable.as_trainable()
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        gen = tc.search_alg or BasicVariantGenerator(
            self.param_space, tc.num_samples, tc.seed)
        trials = [Trial(cfg) for cfg in gen]
        stop = getattr(self.run_config, "stop", None) if self.run_config else None
        failure = getattr(self.run_config, "failure_config", None) \
            if self.run_config else None
        runner = TrialRunner(
            self.trainable, trials, scheduler=tc.scheduler,
            max_concurrent=tc.max_concurrent_trials,
            max_failures=failure.max_failures if failure else 0,
            stop=stop, metric=tc.metric, mode=tc.mode)
        runner.run()
        self._save_experiment_state(trials)
        return ResultGrid(trials, tc.metric, tc.mode)

    def _save_experiment_state(self, trials: List[Trial]):
        run = self.run_config
        path = getattr(run, "storage_path", None) if run else None
        if not path:
            return
        name = getattr(run, "name", None) or "experiment"
        os.makedirs(os.path.join(path, name), exist_ok=True)
        state = [{
            "id": t.id, "config": t.config, "status": t.status,
            "last_result": t.last_result, "error": repr(t.error) if t.error else None,
        } for t in trials]
        with open(os.path.join(path, name, "experiment_state.pkl"), "wb") as f:
            pickle.dump(state, f)


def run(trainable: Callable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, scheduler: Optional[TrialScheduler] = None,
        metric: Optional[str] = None, mode: str = "max",
        stop: Optional[Dict[str, Any]] = None,
        max_concurrent_trials: Optional[int] = None) -> ResultGrid:
    """tune.run-style entry point (reference: python/ray/tune/tune.py:164)."""
    from ray_tpu.air.config import RunConfig

    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler,
                               max_concurrent_trials=max_concurrent_trials),
        run_config=RunConfig(stop=stop))
    return tuner.fit()
