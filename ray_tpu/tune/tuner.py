"""Tuner / tune.run / ResultGrid (reference: python/ray/tune/tuner.py:44,
tune/tune.py:164, tune/result_grid.py)."""
from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.result import Result
from ray_tpu.tune.execution.trial_runner import TrialRunner
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.trial import ERROR, TERMINATED, Trial


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Any] = None  # BasicVariantGenerator default
    seed: Optional[int] = None


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str = "max"):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self.trials)

    def __getitem__(self, i) -> Result:
        t = self.trials[i]
        return Result(metrics=t.last_result, checkpoint=t.checkpoint,
                      error=t.error, metrics_history=t.metrics_history)

    @property
    def errors(self) -> List[BaseException]:
        return [t.error for t in self.trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required")
        sign = 1 if mode == "max" else -1
        done = [t for t in self.trials if t.last_result.get(metric) is not None]
        if not done:
            raise ValueError("no trial reported the metric")
        best = max(done, key=lambda t: sign * t.last_result[metric])
        return Result(metrics=best.last_result, checkpoint=best.checkpoint,
                      metrics_history=best.metrics_history)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([{"trial_id": t.id, **t.config, **t.last_result}
                             for t in self.trials])


class Tuner:
    def __init__(self, trainable: Callable,
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None):
        # Trainers (BaseTrainer) are adapted via as_trainable().
        from ray_tpu.train.base_trainer import BaseTrainer

        if isinstance(trainable, BaseTrainer):
            trainable = trainable.as_trainable()
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config

    def fit(self) -> ResultGrid:
        from ray_tpu.tune.search.tpe import Searcher

        tc = self.tune_config
        searcher = None
        num_samples = 0
        if getattr(self, "_restored_trials", None) is not None:
            # Experiment resume: finished trials keep their results,
            # unfinished ones re-enter the pending queue (from their last
            # checkpoint, if any).
            trials = self._restored_trials
            if isinstance(tc.search_alg, Searcher):
                # Re-arm the searcher: replay finished observations into
                # its model and restore the remaining suggestion budget.
                searcher = tc.search_alg
                searcher.set_search_properties(tc.metric, tc.mode)
                for t in trials:
                    if t.status == TERMINATED and t.last_result:
                        searcher.add_evaluated_point(t.config, t.last_result)
                num_samples = max(0, tc.num_samples - len(trials))
        elif isinstance(tc.search_alg, Searcher):
            searcher = tc.search_alg
            searcher.set_search_properties(tc.metric, tc.mode)
            num_samples = tc.num_samples
            trials = []
        else:
            gen = tc.search_alg or BasicVariantGenerator(
                self.param_space, tc.num_samples, tc.seed)
            trials = [Trial(cfg) for cfg in gen]
        stop = getattr(self.run_config, "stop", None) if self.run_config else None
        failure = getattr(self.run_config, "failure_config", None) \
            if self.run_config else None
        runner = TrialRunner(
            self.trainable, trials, scheduler=tc.scheduler,
            max_concurrent=tc.max_concurrent_trials,
            max_failures=failure.max_failures if failure else 0,
            stop=stop, metric=tc.metric, mode=tc.mode,
            searcher=searcher, num_samples=num_samples,
            on_trial_terminal=lambda _t: self._save_experiment_state(trials))
        runner.run()
        self._save_experiment_state(trials, final=True)
        return ResultGrid(trials, tc.metric, tc.mode)

    # ---- experiment durability (reference: experiment checkpointing +
    # Tuner.restore, python/ray/tune/impl/tuner_internal.py:227) ----
    def _experiment_dir(self) -> Optional[str]:
        run = self.run_config
        path = getattr(run, "storage_path", None) if run else None
        if not path:
            return None
        name = getattr(run, "name", None) or "experiment"
        return os.path.join(path, name)

    def _save_experiment_state(self, trials: List[Trial], final: bool = False):
        exp_dir = self._experiment_dir()
        if not exp_dir:
            return
        os.makedirs(exp_dir, exist_ok=True)
        state = {
            "trials": [{
                "id": t.id, "config": t.config, "status": t.status,
                "last_result": t.last_result,
                "metrics_history": t.metrics_history,
                "checkpoint": t.checkpoint.to_dict() if t.checkpoint else None,
                "error": repr(t.error) if t.error else None,
            } for t in trials],
            "final": final,
        }
        tmp = os.path.join(exp_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, os.path.join(exp_dir, "experiment_state.pkl"))
        # The trainable is immutable during a run — serialize it once, not
        # on every per-trial save (it can close over large objects).
        tpath = os.path.join(exp_dir, "trainable.pkl")
        if not os.path.exists(tpath):
            try:  # rides along so restore() can rebuild alone
                import cloudpickle

                blob = cloudpickle.dumps(self.trainable)
                with open(tpath, "wb") as f:
                    f.write(blob)
            except Exception:
                pass  # restore() then requires trainable= to be passed
        # Mirror LAST, after trainable.pkl exists — a crash between the
        # first sync and the next must not leave a durable copy that
        # restore() can't rebuild from.
        sync_cfg = getattr(self.run_config, "sync_config", None)
        if sync_cfg is not None and sync_cfg.upload_dir:
            from ray_tpu.tune.syncer import Syncer

            if getattr(self, "_syncer", None) is None:
                self._syncer = Syncer(sync_cfg.upload_dir,
                                      sync_cfg.sync_period_s)
            if final:
                self._syncer.sync_now(exp_dir)
            else:
                self._syncer.sync_if_due(exp_dir)

    @classmethod
    def restore(cls, path: str, trainable: Optional[Callable] = None,
                tune_config: Optional[TuneConfig] = None,
                run_config=None) -> "Tuner":
        """Resume an experiment from its storage dir: TERMINATED trials keep
        their results without re-running; unfinished/errored trials are
        re-queued from their last checkpoint."""
        from ray_tpu.air.checkpoint import Checkpoint

        with open(os.path.join(path, "experiment_state.pkl"), "rb") as f:
            state = pickle.load(f)
        if trainable is None:
            import cloudpickle

            with open(os.path.join(path, "trainable.pkl"), "rb") as f:
                trainable = cloudpickle.loads(f.read())
        if run_config is None:
            from ray_tpu.air.config import RunConfig

            run_config = RunConfig(storage_path=os.path.dirname(path),
                                   name=os.path.basename(path))
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config)
        trials = []
        for ts in state["trials"]:
            t = Trial(ts["config"], trial_id=ts["id"])
            t.last_result = ts["last_result"]
            t.metrics_history = ts["metrics_history"]
            if ts["checkpoint"] is not None:
                t.checkpoint = Checkpoint.from_dict(ts["checkpoint"])
            if ts["status"] == TERMINATED:
                t.status = TERMINATED
            # PENDING is Trial's initial status: RUNNING/ERROR re-queue too.
            trials.append(t)
        tuner._restored_trials = trials
        return tuner


def run(trainable: Callable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, scheduler: Optional[TrialScheduler] = None,
        metric: Optional[str] = None, mode: str = "max",
        stop: Optional[Dict[str, Any]] = None,
        max_concurrent_trials: Optional[int] = None) -> ResultGrid:
    """tune.run-style entry point (reference: python/ray/tune/tune.py:164)."""
    from ray_tpu.air.config import RunConfig

    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler,
                               max_concurrent_trials=max_concurrent_trials),
        run_config=RunConfig(stop=stop))
    return tuner.fit()
