"""Trial state (reference: python/ray/tune/experiment/trial.py)."""
from __future__ import annotations

import uuid
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, config: Dict[str, Any], trial_id: Optional[str] = None):
        self.id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.status = PENDING
        self.last_result: Dict[str, Any] = {}
        self.metrics_history: list = []
        self.checkpoint: Optional[Checkpoint] = None
        self.error: Optional[BaseException] = None
        self.actor = None
        self.num_failures = 0
        self.rungs_passed: set = set()  # ASHA bookkeeping

    def __repr__(self):
        return f"Trial({self.id}, {self.status}, {self.config})"
