"""Experiment-dir syncer: mirror trial/experiment state to durable
storage (reference: python/ray/tune/syncer.py — the _DefaultSyncer that
uploads the experiment dir; cloud URIs reduce to a local mount here, the
honest scope for a zero-egress environment)."""
from __future__ import annotations

import os
import shutil
import time
from typing import Optional


class Syncer:
    def __init__(self, upload_dir: str, sync_period_s: float = 0.0):
        self.upload_dir = upload_dir
        self.sync_period_s = sync_period_s
        self._last_sync = 0.0

    def sync_if_due(self, exp_dir: str):
        if self.sync_period_s > 0 and \
                time.time() - self._last_sync < self.sync_period_s:
            return False
        self.sync_now(exp_dir)
        return True

    def sync_now(self, exp_dir: str):
        """Incremental mirror: copy files whose mtime/size changed."""
        dst_root = os.path.join(self.upload_dir,
                                os.path.basename(exp_dir.rstrip("/")))
        for root, _dirs, files in os.walk(exp_dir):
            rel = os.path.relpath(root, exp_dir)
            dst_dir = os.path.join(dst_root, rel) if rel != "." else dst_root
            os.makedirs(dst_dir, exist_ok=True)
            for name in files:
                if name.endswith(".tmp"):
                    continue  # in-flight atomic writes
                src = os.path.join(root, name)
                dst = os.path.join(dst_dir, name)
                try:
                    s = os.stat(src)
                    if os.path.exists(dst):
                        d = os.stat(dst)
                        # Nanosecond mtimes: a same-size rewrite (e.g. the
                        # final save flipping one pickled bool) still gets
                        # a fresh mtime_ns from os.replace, so it syncs;
                        # second-granularity st_mtime would skip it.
                        if d.st_mtime_ns >= s.st_mtime_ns \
                                and d.st_size == s.st_size:
                            continue
                    shutil.copy2(src, dst)
                except OSError:
                    continue  # file vanished mid-walk; next sync catches it
        self._last_sync = time.time()
