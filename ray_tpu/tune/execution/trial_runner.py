"""TrialRunner: the Tune execution engine.

Reference: python/ray/tune/execution/trial_runner.py:268 (step :931) +
RayTrialExecutor (ray_trial_executor.py:191).  Each trial runs as a
_TrialActor: a remote actor executing the trainable function on a
``flow.Stage`` sink worker (the async dataflow substrate owns the
thread lifecycle — same migration as the serve batcher and the engine
loop) and streaming reports through a queue, same mechanism as Train's
TrainWorker.  The runner multiplexes trial results with ray_tpu.wait,
feeds the scheduler, and applies STOP/exploit decisions.
"""
from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.tune import trial as trial_mod
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.trial import ERROR, PENDING, RUNNING, TERMINATED, Trial


@ray_tpu.remote
class _TrialActor:
    def __init__(self, fn, config: dict, checkpoint=None):
        import queue

        # Lazy: ray_tpu.parallel's __init__ pulls jax; trial actors that
        # never run a jax trainable shouldn't pay the import at module
        # scope (the serve batcher's rule).
        from ray_tpu.parallel import flow

        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()

        def report_fn(metrics, ckpt):
            self._q.put(("report", metrics, ckpt))
            if self._stop.is_set():
                raise SystemExit  # cooperative stop at next report

        def run(_item):
            from ray_tpu.air import session as air_session

            air_session.init_session(report_fn=report_fn,
                                     checkpoint=checkpoint)
            try:
                import inspect

                params = []
                try:
                    params = list(inspect.signature(fn).parameters)
                except (TypeError, ValueError):
                    pass
                out = fn(config) if params else fn()
                self._q.put(("done", out, None))
            except SystemExit:
                self._q.put(("done", None, None))
            except BaseException as e:  # noqa: BLE001
                import traceback as tb

                self._q.put(("error", e, tb.format_exc()))
            finally:
                air_session.shutdown_session()

        # One-item sink stage: the worker thread runs the trainable to
        # completion (reports stream through the queue as side effects),
        # then the source exhausts and the substrate retires the thread.
        self._stage = flow.Stage(iter([None]), run, sink=True, workers=1,
                                 name="tune_trial", export_metrics=False)

    def next_result(self, timeout: float = 600.0):
        import queue

        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return ("timeout", None, None)

    def request_stop(self):
        self._stop.set()
        return True


class TrialRunner:
    def __init__(self, trainable: Callable, trials: List[Trial],
                 scheduler: Optional[TrialScheduler] = None,
                 max_concurrent: Optional[int] = None,
                 max_failures: int = 0,
                 stop: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 searcher=None, num_samples: int = 0,
                 on_trial_terminal: Optional[Callable] = None):
        self.trainable = trainable
        self.trials = trials
        self.scheduler = scheduler or FIFOScheduler()
        self.max_concurrent = max_concurrent or 4
        self.max_failures = max_failures
        self.stop_criteria = stop or {}
        self.metric = metric
        self.mode = mode
        # Feedback-driven search: trials are created lazily from
        # searcher.suggest() as slots free up, so later suggestions see
        # earlier results (reference: SearchGenerator,
        # tune/search/search_generator.py).
        self.searcher = searcher
        self.num_samples = num_samples
        self.on_trial_terminal = on_trial_terminal

    def _next_suggested_trial(self) -> Optional[Trial]:
        if self.searcher is None or self.num_samples <= 0:
            return None
        trial_id = f"t{len(self.trials):04d}"
        cfg = self.searcher.suggest(trial_id)
        if cfg is None:
            self.num_samples = 0
            return None
        self.num_samples -= 1
        t = Trial(cfg, trial_id=trial_id)
        self.trials.append(t)
        return t

    def _notify_terminal(self, trial: Trial):
        if self.searcher is not None:
            try:
                self.searcher.on_trial_complete(trial.id, trial.last_result)
            except Exception:
                traceback.print_exc()
        if self.on_trial_terminal is not None:
            try:
                self.on_trial_terminal(trial)
            except Exception:
                traceback.print_exc()

    # ---- PBT hook ----
    def exploit(self, trial: Trial, source: Trial, new_config: dict):
        """Replace `trial` with a clone of `source` (checkpoint + mutated
        config) — requires trainables that honor session.get_checkpoint."""
        if source.checkpoint is None:
            return
        self._stop_actor(trial)
        trial.config = new_config
        trial.checkpoint = source.checkpoint
        trial.rungs_passed = set()
        self._launch(trial)

    # ---- execution ----
    def _launch(self, trial: Trial):
        trial.status = RUNNING
        trial.actor = _TrialActor.options(max_concurrency=2).remote(
            self.trainable, trial.config, trial.checkpoint)

    def _stop_actor(self, trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None

    def run(self) -> List[Trial]:
        pending = [t for t in self.trials if t.status == PENDING]
        active: Dict[Any, tuple] = {}  # future -> (trial, actor-at-poll-time)

        def poll(trial: Trial):
            fut = trial.actor.next_result.remote(timeout=600.0)
            active[fut] = (trial, trial.actor)

        while pending or active or (self.searcher and self.num_samples > 0):
            while len({t[0].id for t in active.values()}) \
                    < self.max_concurrent:
                if pending:
                    t = pending.pop(0)
                else:
                    t = self._next_suggested_trial()
                    if t is None:
                        break
                self._launch(t)
                poll(t)
            if not active:
                continue
            ready, _ = ray_tpu.wait(list(active.keys()), num_returns=1,
                                    timeout=60.0)
            if not ready:
                continue
            fut = ready[0]
            trial, actor = active.pop(fut)
            if trial.actor is not actor:
                # Stale future from a pre-exploit actor: poll the new one.
                if trial.actor is not None:
                    poll(trial)
                continue
            try:
                kind, payload, extra = ray_tpu.get(fut)
            except Exception as e:  # actor died
                self._on_trial_error(trial, e, pending)
                continue
            if kind == "report":
                trial.last_result = payload
                trial.metrics_history.append(payload)
                if extra is not None:
                    trial.checkpoint = extra
                decision = self.scheduler.on_trial_result(self, trial, payload)
                if self._hit_stop_criteria(payload) or decision == STOP:
                    self._terminate(trial)
                elif trial.actor is not None:
                    poll(trial)
            elif kind == "done":
                trial.status = TERMINATED
                self.scheduler.on_trial_complete(self, trial,
                                                 trial.last_result)
                self._stop_actor(trial)
                self._notify_terminal(trial)
            elif kind == "error":
                self._on_trial_error(
                    trial, payload if isinstance(payload, BaseException)
                    else RuntimeError(str(extra)), pending)
            elif kind == "timeout":
                poll(trial)
        return self.trials

    def _terminate(self, trial: Trial):
        trial.status = TERMINATED
        self.scheduler.on_trial_complete(self, trial, trial.last_result)
        self._stop_actor(trial)
        self._notify_terminal(trial)

    def _on_trial_error(self, trial: Trial, error: BaseException,
                        pending: List[Trial]):
        self._stop_actor(trial)
        trial.num_failures += 1
        if self.max_failures < 0 or trial.num_failures <= self.max_failures:
            trial.status = PENDING
            pending.append(trial)  # retry (restores last checkpoint)
        else:
            trial.status = ERROR
            trial.error = error
            self._notify_terminal(trial)

    def _hit_stop_criteria(self, result: Dict[str, Any]) -> bool:
        return any(result.get(k) is not None and result[k] >= v
                   for k, v in self.stop_criteria.items())
