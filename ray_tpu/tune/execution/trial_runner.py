"""TrialRunner: the Tune execution engine.

Reference: python/ray/tune/execution/trial_runner.py:268 (step :931) +
RayTrialExecutor (ray_trial_executor.py:191).  Each trial runs as a
_TrialActor (a remote actor executing the trainable function in a thread and
streaming reports through a queue — same mechanism as Train's TrainWorker).
The runner multiplexes trial results with ray_tpu.wait, feeds the scheduler,
and applies STOP/exploit decisions.
"""
from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.tune import trial as trial_mod
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.trial import ERROR, PENDING, RUNNING, TERMINATED, Trial


@ray_tpu.remote
class _TrialActor:
    def __init__(self, fn, config: dict, checkpoint=None):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()

        def report_fn(metrics, ckpt):
            self._q.put(("report", metrics, ckpt))
            if self._stop.is_set():
                raise SystemExit  # cooperative stop at next report

        def run():
            from ray_tpu.air import session as air_session

            air_session.init_session(report_fn=report_fn,
                                     checkpoint=checkpoint)
            try:
                import inspect

                params = []
                try:
                    params = list(inspect.signature(fn).parameters)
                except (TypeError, ValueError):
                    pass
                out = fn(config) if params else fn()
                self._q.put(("done", out, None))
            except SystemExit:
                self._q.put(("done", None, None))
            except BaseException as e:  # noqa: BLE001
                import traceback as tb

                self._q.put(("error", e, tb.format_exc()))
            finally:
                air_session.shutdown_session()

        threading.Thread(target=run, daemon=True, name="trial").start()

    def next_result(self, timeout: float = 600.0):
        import queue

        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return ("timeout", None, None)

    def request_stop(self):
        self._stop.set()
        return True


class TrialRunner:
    def __init__(self, trainable: Callable, trials: List[Trial],
                 scheduler: Optional[TrialScheduler] = None,
                 max_concurrent: Optional[int] = None,
                 max_failures: int = 0,
                 stop: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max"):
        self.trainable = trainable
        self.trials = trials
        self.scheduler = scheduler or FIFOScheduler()
        self.max_concurrent = max_concurrent or 4
        self.max_failures = max_failures
        self.stop_criteria = stop or {}
        self.metric = metric
        self.mode = mode

    # ---- PBT hook ----
    def exploit(self, trial: Trial, source: Trial, new_config: dict):
        """Replace `trial` with a clone of `source` (checkpoint + mutated
        config) — requires trainables that honor session.get_checkpoint."""
        if source.checkpoint is None:
            return
        self._stop_actor(trial)
        trial.config = new_config
        trial.checkpoint = source.checkpoint
        trial.rungs_passed = set()
        self._launch(trial)

    # ---- execution ----
    def _launch(self, trial: Trial):
        trial.status = RUNNING
        trial.actor = _TrialActor.options(max_concurrency=2).remote(
            self.trainable, trial.config, trial.checkpoint)

    def _stop_actor(self, trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None

    def run(self) -> List[Trial]:
        pending = [t for t in self.trials if t.status == PENDING]
        active: Dict[Any, tuple] = {}  # future -> (trial, actor-at-poll-time)

        def poll(trial: Trial):
            fut = trial.actor.next_result.remote(timeout=600.0)
            active[fut] = (trial, trial.actor)

        while pending or active:
            while pending and len({t[0].id for t in active.values()}) \
                    < self.max_concurrent:
                t = pending.pop(0)
                self._launch(t)
                poll(t)
            if not active:
                continue
            ready, _ = ray_tpu.wait(list(active.keys()), num_returns=1,
                                    timeout=60.0)
            if not ready:
                continue
            fut = ready[0]
            trial, actor = active.pop(fut)
            if trial.actor is not actor:
                # Stale future from a pre-exploit actor: poll the new one.
                if trial.actor is not None:
                    poll(trial)
                continue
            try:
                kind, payload, extra = ray_tpu.get(fut)
            except Exception as e:  # actor died
                self._on_trial_error(trial, e, pending)
                continue
            if kind == "report":
                trial.last_result = payload
                trial.metrics_history.append(payload)
                if extra is not None:
                    trial.checkpoint = extra
                decision = self.scheduler.on_trial_result(self, trial, payload)
                if self._hit_stop_criteria(payload) or decision == STOP:
                    self._terminate(trial)
                elif trial.actor is not None:
                    poll(trial)
            elif kind == "done":
                trial.status = TERMINATED
                self.scheduler.on_trial_complete(self, trial,
                                                 trial.last_result)
                self._stop_actor(trial)
            elif kind == "error":
                self._on_trial_error(
                    trial, payload if isinstance(payload, BaseException)
                    else RuntimeError(str(extra)), pending)
            elif kind == "timeout":
                poll(trial)
        return self.trials

    def _terminate(self, trial: Trial):
        trial.status = TERMINATED
        self.scheduler.on_trial_complete(self, trial, trial.last_result)
        self._stop_actor(trial)

    def _on_trial_error(self, trial: Trial, error: BaseException,
                        pending: List[Trial]):
        self._stop_actor(trial)
        trial.num_failures += 1
        if self.max_failures < 0 or trial.num_failures <= self.max_failures:
            trial.status = PENDING
            pending.append(trial)  # retry (restores last checkpoint)
        else:
            trial.status = ERROR
            trial.error = error

    def _hit_stop_criteria(self, result: Dict[str, Any]) -> bool:
        return any(result.get(k) is not None and result[k] >= v
                   for k, v in self.stop_criteria.items())
