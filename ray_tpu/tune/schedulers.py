"""Trial schedulers: FIFO, ASHA, PBT.

Reference: python/ray/tune/schedulers/ — ASHA (async_hyperband.py:17, rung
cutoff quantiles at :138,220), PBT (pbt.py: exploit top quantile :791,
explore/mutate :48, quantiles :868).
"""
from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: rung-based async successive halving."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        # Rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.milestones: List[float] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # rung milestone -> list of recorded metric values
        self.rungs: Dict[float, List[float]] = {m: [] for m in self.milestones}

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        value = score if self.mode == "max" else -score
        for m in self.milestones:
            if t >= m and m not in trial.rungs_passed:
                trial.rungs_passed.add(m)
                recorded = self.rungs[m]
                recorded.append(value)
                if len(recorded) >= max(2, int(self.rf)):
                    top_k = max(1, int(len(recorded) / self.rf))
                    cutoff = sorted(recorded, reverse=True)[top_k - 1]
                    if value < cutoff:
                        return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: bottom-quantile trials clone a top trial's checkpoint and mutate
    hyperparameters.  Requires trials to report checkpoints."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: int = 0):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self.last_perturb: Dict[str, float] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        if t - self.last_perturb.get(trial.id, 0) < self.interval:
            return CONTINUE
        self.last_perturb[trial.id] = t
        trials = [tr for tr in runner.trials if tr.last_result]
        if len(trials) < 2:
            return CONTINUE
        key = lambda tr: tr.last_result.get(self.metric, -math.inf) \
            * (1 if self.mode == "max" else -1)
        ranked = sorted(trials, key=key)
        n_q = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[:n_q]
        top = ranked[-n_q:]
        if trial in bottom:
            source = self.rng.choice(top)
            if source is trial:
                return CONTINUE
            new_config = dict(source.config)
            for name, mut in self.mutations.items():
                old = new_config.get(name)
                if isinstance(mut, list):
                    new_config[name] = self.rng.choice(mut)
                elif callable(mut):
                    new_config[name] = mut()
                elif old is not None:
                    factor = self.rng.choice([0.8, 1.2])
                    new_config[name] = old * factor
            runner.exploit(trial, source, new_config)
        return CONTINUE
