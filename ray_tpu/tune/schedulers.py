"""Trial schedulers: FIFO, ASHA, PBT.

Reference: python/ray/tune/schedulers/ — ASHA (async_hyperband.py:17, rung
cutoff quantiles at :138,220), PBT (pbt.py: exploit top quantile :791,
explore/mutate :48, quantiles :868).
"""
from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: rung-based async successive halving."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        # Rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.milestones: List[float] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # rung milestone -> list of recorded metric values
        self.rungs: Dict[float, List[float]] = {m: [] for m in self.milestones}

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        value = score if self.mode == "max" else -score
        for m in self.milestones:
            if t >= m and m not in trial.rungs_passed:
                trial.rungs_passed.add(m)
                recorded = self.rungs[m]
                recorded.append(value)
                if len(recorded) >= max(2, int(self.rf)):
                    top_k = max(1, int(len(recorded) / self.rf))
                    cutoff = sorted(recorded, reverse=True)[top_k - 1]
                    if value < cutoff:
                        return STOP
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """HyperBand: multiple successive-halving brackets with staggered
    grace periods, so some brackets explore many short trials while others
    give fewer trials a longer runway (reference:
    python/ray/tune/schedulers/hyperband.py — realized here as async
    brackets sharing the ASHA rung rule, the same relaxation the reference
    recommends via ASHA for distributed use)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "score", mode: str = "max",
                 max_t: int = 81, reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        # Integer multiply-loop, not int(log/log): float truncation would
        # drop the deepest bracket exactly when max_t is a power of rf.
        s_max, t = 0, reduction_factor
        while t <= max_t:
            s_max += 1
            t *= reduction_factor
        s_max = max(1, s_max)
        self.brackets = [
            AsyncHyperBandScheduler(
                time_attr=time_attr, metric=metric, mode=mode, max_t=max_t,
                grace_period=max(1, int(reduction_factor ** s)),
                reduction_factor=reduction_factor)
            for s in range(s_max)
        ]
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def _bracket_of(self, trial):
        b = self._assignment.get(trial.id)
        if b is None:
            b = self._next % len(self.brackets)
            self._assignment[trial.id] = b
            self._next += 1
        return self.brackets[b]

    def on_trial_result(self, runner, trial, result) -> str:
        return self._bracket_of(trial).on_trial_result(runner, trial, result)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average metric falls below the median of
    other trials' running averages at the same time step (reference:
    python/ray/tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "score", mode: str = "max",
                 grace_period: int = 5, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        # trial.id -> (sum, count) of reported metric values.
        self._running: Dict[str, tuple] = {}

    def _avg(self, trial_id) -> Optional[float]:
        s = self._running.get(trial_id)
        return s[0] / s[1] if s and s[1] else None

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return CONTINUE
        sign = 1.0 if self.mode == "max" else -1.0
        s, c = self._running.get(trial.id, (0.0, 0))
        self._running[trial.id] = (s + sign * score, c + 1)
        if t < self.grace:
            return CONTINUE
        others = [self._avg(tr.id) for tr in runner.trials
                  if tr.id != trial.id]
        others = [o for o in others if o is not None]
        if len(others) < self.min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        if self._avg(trial.id) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: bottom-quantile trials clone a top trial's checkpoint and mutate
    hyperparameters.  Requires trials to report checkpoints."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: int = 0):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self.last_perturb: Dict[str, float] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        if t - self.last_perturb.get(trial.id, 0) < self.interval:
            return CONTINUE
        self.last_perturb[trial.id] = t
        trials = [tr for tr in runner.trials if tr.last_result]
        if len(trials) < 2:
            return CONTINUE
        key = lambda tr: tr.last_result.get(self.metric, -math.inf) \
            * (1 if self.mode == "max" else -1)
        ranked = sorted(trials, key=key)
        n_q = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[:n_q]
        top = ranked[-n_q:]
        if trial in bottom:
            source = self.rng.choice(top)
            if source is trial:
                return CONTINUE
            new_config = dict(source.config)
            for name, mut in self.mutations.items():
                old = new_config.get(name)
                if isinstance(mut, list):
                    new_config[name] = self.rng.choice(mut)
                elif callable(mut):
                    new_config[name] = mut()
                elif old is not None:
                    factor = self.rng.choice([0.8, 1.2])
                    new_config[name] = old * factor
            runner.exploit(trial, source, new_config)
        return CONTINUE
