"""@serve.batch — adaptive request batching inside a replica.

Reference: python/ray/serve/batching.py (@serve.batch collects concurrent
calls into one invocation of the underlying function).  TPU-critical: a
replica hosting a pjit-compiled model turns N concurrent single requests
into ONE batched device call, which is the only way the MXU sees a real
batch dimension from a request/response workload.

Mechanics: requests enqueue (item, Future) and block on the future; the
batcher is ONE ``flow.Stage(sink=True)`` over a batch-assembly source —
the source generator drains the queue (first item blocking, then up to
max_batch_size or until batch_wait_timeout_s passes) and yields batches,
the stage worker calls the wrapped function once per batch and resolves
each item's future.  This was the first hand-rolled Thread+Queue loop
migrated onto the async dataflow substrate (tools/check_flow_usage.py's
allowlist-only-shrinks contract): thread lifecycle, cancellation and
join-on-close now come from ``ray_tpu.parallel.flow``.

Failure semantics: an exception from the batched handler is ISOLATED —
each item of the failed batch is retried alone, so only the item whose
handler actually raises sees the exception; its batchmates still get
results (at the cost of re-running their handler calls, so batched
handlers should be idempotent per item).  ``close()`` stops the batcher
stage and wakes queued submitters with a typed
:class:`~ray_tpu.exceptions.BatcherClosedError` — deployment teardown and
``serve.shutdown()`` drain every batcher instead of leaking daemon
threads and permanently-blocked callers.
"""
from __future__ import annotations

import functools
import queue
import threading
import weakref
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

from ray_tpu.exceptions import BatcherClosedError

_CLOSE = object()  # queue sentinel: wake the assembly source for shutdown

# Every live batcher in this process, so teardown paths (serve.shutdown,
# replica drain) can close them without holding the decorated objects.
_BATCHERS: "weakref.WeakSet" = weakref.WeakSet()


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._queue: "queue.Queue" = queue.Queue()
        self._stage: Optional[Any] = None  # flow.Stage (lazy import)
        self._lock = threading.Lock()
        self._closed = False
        _BATCHERS.add(self)

    def _ensure_stage(self):
        # Lazy: ray_tpu.parallel's __init__ pulls jax; the serve package
        # must stay importable without it (same rule as ray_tpu.data).
        from ray_tpu.parallel import flow

        with self._lock:
            if self._closed:
                raise BatcherClosedError(
                    f"batcher for {getattr(self.fn, '__name__', self.fn)!r} "
                    f"is closed")
            if self._stage is None:
                self._stage = flow.Stage(
                    self._batch_source(), self._dispatch, workers=1,
                    depth=1, sink=True, name="serve-batch",
                    export_metrics=False)

    def submit(self, item) -> Any:
        fut: Future = Future()
        self._ensure_stage()
        self._queue.put((item, fut))
        if self._closed:
            # close() raced our put: its drain may already have run, so
            # this future could block forever — fail it here (idempotent
            # if the drain got it first).
            if not fut.done():
                fut.set_exception(BatcherClosedError("batcher closed"))
        return fut.result()

    def _batch_source(self):
        """Batch-assembly source for the sink stage: block for the first
        item, then fill up to max_batch_size or the wait deadline.  The
        _CLOSE sentinel ends the stream (a mid-assembly close still
        yields the partial batch so its callers get results)."""
        import time

        while True:
            item, fut = self._queue.get()
            if item is _CLOSE:
                return
            if self._closed:
                # Drain mode: everything queued at close time is failed,
                # not run — callers wake with the typed error.
                if fut is not None and not fut.done():
                    fut.set_exception(BatcherClosedError(
                        "batcher closed before this request ran"))
                continue
            batch = [(item, fut)]
            deadline = time.monotonic() + self.batch_wait_timeout_s
            closing = False
            while len(batch) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt[0] is _CLOSE:
                    closing = True
                    break
                batch.append(nxt)
            yield batch
            if closing:
                return

    def close(self, timeout: float = 5.0):
        """Stop the batcher stage and fail queued submitters with a
        typed error.  The batch currently executing finishes and its
        callers get their results."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            stage = self._stage
        self._queue.put((_CLOSE, None))
        if stage is not None:
            # Joins the worker thread (the in-flight dispatch completes;
            # the _CLOSE above wakes a source parked on an empty queue).
            stage.close()
        err = BatcherClosedError(
            f"batcher for {getattr(self.fn, '__name__', self.fn)!r} was "
            f"closed before this request ran")
        while True:
            try:
                item, fut = self._queue.get_nowait()
            except queue.Empty:
                return
            if fut is not None and not fut.done():
                fut.set_exception(err)

    def _dispatch(self, batch):
        items = [b[0] for b in batch]
        try:
            results = self.fn(items)
            if results is None or len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function must return one result per "
                    f"input ({len(items)} in, "
                    f"{None if results is None else len(results)} out)")
            for (_, f), r in zip(batch, results):
                f.set_result(r)
        except BaseException as e:  # noqa: BLE001 — delivered to callers
            if len(batch) == 1:
                _, f = batch[0]
                if not f.done():
                    f.set_exception(e)
                return
            # Isolate the offender: one poisoned item must not fail its
            # batchmates.  Re-run each item alone; whoever raises gets
            # their own exception, everyone else a result.
            for it, f in batch:
                if f.done():
                    continue
                try:
                    r = self.fn([it])
                    if r is None or len(r) != 1:
                        raise ValueError(
                            "@serve.batch function must return one result "
                            "per input")
                    f.set_result(r[0])
                except BaseException as ee:  # noqa: BLE001
                    f.set_exception(ee)


def shutdown_batchers():
    """Close every live batcher in this process (serve.shutdown)."""
    for b in list(_BATCHERS):
        try:
            b.close()
        except Exception:
            pass


def close_instance_batchers(obj):
    """Close the per-instance batchers installed on ``obj`` by the method
    form of @serve.batch (replica teardown)."""
    for name, val in list(vars(obj).items()):
        if name.startswith("__rtpu_batcher_") and isinstance(val, _Batcher):
            try:
                val.close()
            except Exception:
                pass


class _BatchDescriptor:
    """Function/method wrapper installing per-instance batchers."""

    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._free_batcher: Optional[_Batcher] = None
        functools.update_wrapper(self, fn)

    # plain-function use
    def __call__(self, item):
        if self._free_batcher is None or self._free_batcher._closed:
            self._free_batcher = _Batcher(self._fn, self._max, self._wait)
        return self._free_batcher.submit(item)

    # method use: one batcher per instance, created on first access
    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        attr = "__rtpu_batcher_" + self._fn.__name__
        batcher = getattr(obj, attr, None)
        if batcher is None or batcher._closed:
            bound = self._fn.__get__(obj, objtype)
            batcher = _Batcher(bound, self._max, self._wait)
            try:
                object.__setattr__(obj, attr, batcher)
            except AttributeError:
                pass  # __slots__: fall back to a fresh batcher per access

        def call(item):
            return batcher.submit(item)

        functools.update_wrapper(call, self._fn)
        return call


def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: fn(list_of_items) -> list_of_results, called with
    auto-collected batches of concurrent single-item requests."""

    def deco(fn):
        return _BatchDescriptor(fn, max_batch_size, batch_wait_timeout_s)

    if _func is not None:
        return deco(_func)
    return deco
