"""@serve.batch — adaptive request batching inside a replica.

Reference: python/ray/serve/batching.py (@serve.batch collects concurrent
calls into one invocation of the underlying function).  TPU-critical: a
replica hosting a pjit-compiled model turns N concurrent single requests
into ONE batched device call, which is the only way the MXU sees a real
batch dimension from a request/response workload.

Mechanics: requests enqueue (item, Future) and block on the future; a
lazily-started batcher thread drains the queue — first item blocking, then
up to max_batch_size or until batch_wait_timeout_s passes — and calls the
wrapped function once with the list of items, distributing results (or the
exception) back.  Works on plain functions and methods (descriptor
protocol keeps one batcher per bound instance).
"""
from __future__ import annotations

import functools
import queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="rtpu-serve-batcher", daemon=True)
                self._thread.start()

    def submit(self, item) -> Any:
        fut: Future = Future()
        self._queue.put((item, fut))
        self._ensure_thread()
        return fut.result()

    def _loop(self):
        import time

        while True:
            item, fut = self._queue.get()
            batch = [(item, fut)]
            deadline = time.monotonic() + self.batch_wait_timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            items = [b[0] for b in batch]
            try:
                results = self.fn(items)
                if results is None or len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch function must return one result per "
                        f"input ({len(items)} in, "
                        f"{None if results is None else len(results)} out)")
                for (_, f), r in zip(batch, results):
                    f.set_result(r)
            except BaseException as e:  # noqa: BLE001 — delivered to callers
                for _, f in batch:
                    if not f.done():
                        f.set_exception(e)


class _BatchDescriptor:
    """Function/method wrapper installing per-instance batchers."""

    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._free_batcher: Optional[_Batcher] = None
        functools.update_wrapper(self, fn)

    # plain-function use
    def __call__(self, item):
        if self._free_batcher is None:
            self._free_batcher = _Batcher(self._fn, self._max, self._wait)
        return self._free_batcher.submit(item)

    # method use: one batcher per instance, created on first access
    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        attr = "__rtpu_batcher_" + self._fn.__name__
        batcher = getattr(obj, attr, None)
        if batcher is None:
            bound = self._fn.__get__(obj, objtype)
            batcher = _Batcher(bound, self._max, self._wait)
            try:
                object.__setattr__(obj, attr, batcher)
            except AttributeError:
                pass  # __slots__: fall back to a fresh batcher per access

        def call(item):
            return batcher.submit(item)

        functools.update_wrapper(call, self._fn)
        return call


def batch(_func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: fn(list_of_items) -> list_of_results, called with
    auto-collected batches of concurrent single-item requests."""

    def deco(fn):
        return _BatchDescriptor(fn, max_batch_size, batch_wait_timeout_s)

    if _func is not None:
        return deco(_func)
    return deco
