"""Disaggregated prefill: dedicated replicas compute KV, decode adopts.

Prefill and decode have opposite hardware appetites — prefill is one
big compute-bound batch over the whole prompt, decode is thousands of
tiny latency-bound steps — so co-locating them makes every long prompt
a decode stall.  This module splits them (the P/D-disaggregation
design from the serving literature, composed Ray-style over the object
plane): a :class:`PrefillWorker` runs bucketed prefill on its own
replica set, packs the produced KV pages into a wire payload
(``native`` fp32, or ``int8`` block-scaled via the
``ops/collectives`` format from the EQuARX wire, arxiv 2506.17615),
publishes the arrays with ``put_many`` and returns the refs — the same
store-to-store ref chaining the MPMD pipeline ships activations with.
The decode engine (`llm_engine.py`) holds the admitted slot, keeps
decoding its active batch, and adopts the pages with ``get_many`` +
one compiled scatter when the refs resolve.

:class:`PrefillClient` normalizes the three ways a prefill target can
be reached — a serve ``DeploymentHandle`` (autoscaled replica set), a
raw actor handle, or an in-process :class:`PrefillWorker` (tests,
single-host deployments) — behind ``submit()/poll()`` so the engine
loop never blocks on a prompt.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.serve.sampling import SamplingParams

_DEF = object()


def _plane_up() -> bool:
    try:
        import ray_tpu

        return ray_tpu.is_initialized()
    except Exception:
        return False


# ---------------------------------------------------------------------------
# KV page wire format
# ---------------------------------------------------------------------------
def pack_pages(k: np.ndarray, v: np.ndarray,
               wire_dtype: str = "native") -> Dict[str, Any]:
    """Pack [L, n_pages, ps, Hkv, D] K/V page arrays for the wire.

    ``native`` ships fp32 (exact — bf16/f32 caches round-trip
    losslessly, so adopted pages are bit-identical to locally-prefilled
    ones and the token-identity gates hold).  ``int8`` block-scales
    the head_dim axis with the ops/collectives numpy mirror (~3.5-4x
    smaller; approximate, so the engine skips re-publishing such pages
    into the exact prefix cache)."""
    k = np.asarray(k, np.float32)
    v = np.asarray(v, np.float32)
    fp32_bytes = int(k.nbytes + v.nbytes)
    if wire_dtype == "native":
        payload = {"fmt": "native", "k": k, "v": v}
    elif wire_dtype == "int8":
        from ray_tpu.ops.collectives import quantize_block_int8_np

        block = k.shape[-1]
        kq, ks = quantize_block_int8_np(k, block)
        vq, vs = quantize_block_int8_np(v, block)
        payload = {"fmt": "int8", "kq": kq, "ks": ks, "vq": vq, "vs": vs,
                   "block": block, "n": k.shape[-1]}
    else:
        raise ValueError(f"unknown wire_dtype {wire_dtype!r}")
    wire = sum(int(a.nbytes) for a in payload.values()
               if isinstance(a, np.ndarray))
    payload["wire_bytes"] = wire
    payload["fp32_bytes"] = fp32_bytes
    return payload


def unpack_pages(payload: Dict[str, Any]) -> Tuple[np.ndarray, np.ndarray]:
    if payload["fmt"] == "native":
        return payload["k"], payload["v"]
    from ray_tpu.ops.collectives import dequantize_block_int8_np

    n = int(payload["n"])
    k = dequantize_block_int8_np(payload["kq"], payload["ks"], n)
    v = dequantize_block_int8_np(payload["vq"], payload["vs"], n)
    return k, v


_WIRE_ARRAYS = {"native": ("k", "v"), "int8": ("kq", "ks", "vq", "vs")}


class PrefillWorker:
    """Stateless bucketed-prefill replica.

    One compiled program per power-of-two prompt bucket (the engine's
    prefill bucketing, minus the page scatter — the worker returns the
    raw per-position KV, chopped into pages host-side).  ``prefill``
    also samples the next token with the request's seeded sampler, so
    the decode replica starts from exactly the token a local prefill
    would have produced (replicas share seeded-identical weights).

    Deploy under ``@serve.deployment`` (its own autoscaling config —
    prefill replicas scale on prompt load, decode replicas on decode
    load) or instantiate in-process."""

    def __init__(self, model_kind: str = "gpt2",
                 config_kw: Optional[dict] = None, seed: int = 0,
                 page_size=_DEF, max_ctx: Optional[int] = None,
                 wire_dtype: str = "native",
                 use_object_plane: Optional[bool] = None):
        import jax  # noqa: F401 — fail here, not mid-request

        from ray_tpu.serve.llm_engine import _cfg, build_model

        self._model, self._params = build_model(model_kind, config_kw, seed)
        c = self._model.config
        self.page_size = int(_cfg("serve_page_size", page_size, 16))
        self.max_ctx = int(max_ctx or c.max_position_embeddings)
        self.wire_dtype = wire_dtype
        self._use_plane = use_object_plane
        self.num_layers = c.num_layers
        self.kv_heads = getattr(c, "num_kv_heads", c.num_heads)
        self.head_dim = c.head_dim
        self.dtype = c.dtype
        self._fns: Dict[int, Any] = {}
        self._stats = {"requests": 0, "tokens": 0, "wire_bytes": 0,
                       "fp32_bytes": 0}

    def _bucket_for(self, p: int) -> int:
        b = 8
        while b < p:
            b <<= 1
        return min(b, self.max_ctx)

    def _fn(self, bucket: int):
        fn = self._fns.get(bucket)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from ray_tpu.serve.sampling import sample_tokens_with_logprobs

        model, L = self._model, self.num_layers
        hkv, d, dt = self.kv_heads, self.head_dim, self.dtype

        def prefill(params, tokens, p, temp, top_p, seed):
            ids = tokens[None]
            positions = jnp.arange(bucket)[None]
            empty = [(jnp.zeros((1, 0, hkv, d), dt),) * 2 for _ in range(L)]
            logits, new_kvs = model.apply(
                {"params": params}, ids, positions, empty,
                jnp.zeros((1,), jnp.int32))
            toks, logps = sample_tokens_with_logprobs(
                logits[0, p - 1][None], jnp.reshape(p, (1,)),
                jnp.reshape(temp, (1,)), jnp.reshape(top_p, (1,)),
                jnp.reshape(seed, (1,)))
            newk = jnp.stack([nk[0][0] for nk in new_kvs])  # [L,bkt,Hkv,D]
            newv = jnp.stack([nk[1][0] for nk in new_kvs])
            return newk, newv, toks[0], logps[0]

        fn = jax.jit(prefill)
        self._fns[bucket] = fn
        return fn

    def prefill(self, tokens, start: int = 0, temperature: float = 0.0,
                top_p: float = 1.0, seed: int = 0) -> Dict[str, Any]:
        """Compute KV for ``tokens`` and return the pages covering
        positions ``[start, len(tokens))`` (``start`` is the decode
        side's cached-prefix length, page-aligned — attention needs the
        whole prompt, the wire only the uncached tail) plus the sampled
        next token.  With a connected object plane the page arrays ride
        ``put_many`` and the return value carries refs."""
        tokens = [int(t) for t in np.asarray(tokens).reshape(-1)]
        p = len(tokens)
        if not p:
            raise ValueError("empty prompt")
        if start % self.page_size:
            raise ValueError(f"start {start} is not page-aligned "
                             f"(page_size {self.page_size})")
        bucket = self._bucket_for(p)
        toks = np.zeros((bucket,), np.int32)
        toks[:p] = tokens
        newk, newv, nxt, nxt_logp = self._fn(bucket)(
            self._params, toks, np.int32(p), np.float32(temperature),
            np.float32(top_p), np.int32(seed))
        ps = self.page_size
        n0, n1 = start // ps, math.ceil(p / ps)
        buf_shape = (self.num_layers, n1 * ps, self.kv_heads, self.head_dim)
        bk = np.zeros(buf_shape, np.float32)
        bv = np.zeros(buf_shape, np.float32)
        bk[:, :p] = np.asarray(newk, np.float32)[:, :p]
        bv[:, :p] = np.asarray(newv, np.float32)[:, :p]
        pk = bk.reshape(self.num_layers, n1, ps, self.kv_heads,
                        self.head_dim)[:, n0:]
        pv = bv.reshape(self.num_layers, n1, ps, self.kv_heads,
                        self.head_dim)[:, n0:]
        payload = pack_pages(pk, pv, self.wire_dtype)
        payload.update(next_token=int(nxt), next_logp=float(nxt_logp),
                       p=p, start=start)
        self._stats["requests"] += 1
        self._stats["tokens"] += p - start
        self._stats["wire_bytes"] += payload["wire_bytes"]
        self._stats["fp32_bytes"] += payload["fp32_bytes"]
        use_plane = self._use_plane if self._use_plane is not None \
            else _plane_up()
        if use_plane:
            import ray_tpu

            names = _WIRE_ARRAYS[payload["fmt"]]
            refs = ray_tpu.put_many([payload.pop(n) for n in names])
            payload["refs"] = refs
            payload["ref_names"] = list(names)
        return payload

    def prefill_many(self, requests: List[dict]) -> List[Dict[str, Any]]:
        """Batched entry point (one RPC, one coalesced ``put_many`` ride
        per request): each request is the kwargs of :meth:`prefill`."""
        return [self.prefill(**r) for r in requests]

    def stats(self) -> Dict[str, int]:
        out = dict(self._stats)
        out["buckets"] = len(self._fns)
        return out

    def drain(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# Client side (lives inside the decode engine's loop)
# ---------------------------------------------------------------------------
class _PrefillJob:
    """One in-flight prefill.  ``poll()`` returns None while pending,
    else ``(k, v, next_token, meta)`` with [L, n_pages, ps, Hkv, D]
    float32 page arrays; raises the remote error, typed."""

    def __init__(self, future=None, payload=None):
        self._future = future
        self._payload = payload
        self._delivered = False

    def poll(self):
        if self._delivered:
            return None
        if self._payload is None:
            if self._future is None or not self._future.done():
                return None
            self._payload = self._future.result()
        self._delivered = True
        return _resolve_payload(self._payload)


def _resolve_payload(payload: Dict[str, Any]):
    payload = dict(payload)
    refs = payload.pop("refs", None)
    if refs is not None:
        import ray_tpu

        vals = ray_tpu.get_many(list(refs))
        payload.update(zip(payload.pop("ref_names"), vals))
    k, v = unpack_pages(payload)
    meta = {"wire_bytes": payload["wire_bytes"],
            "fp32_bytes": payload["fp32_bytes"],
            "exact": payload["fmt"] == "native",
            "next_logp": payload.get("next_logp", float("nan"))}
    return k, v, payload["next_token"], meta


class PrefillClient:
    """Engine-facing adapter over a prefill target: a serve
    DeploymentHandle (``.method``), an actor handle (``.prefill.remote``)
    or an in-process PrefillWorker.  A local worker runs on a
    background thread (jit dispatch releases the GIL into XLA), so even
    single-process disaggregation overlaps prefill with the engine's
    decode loop — the whole point of the split."""

    def __init__(self, target):
        self._target = target
        self._pool = None
        if hasattr(target, "method"):
            self._kind = "deployment"
        elif hasattr(getattr(target, "prefill", None), "remote"):
            self._kind = "actor"
        elif callable(getattr(target, "prefill", None)):
            self._kind = "local"
        else:
            raise TypeError(
                f"not a prefill target: {type(target).__name__} (need a "
                "DeploymentHandle, an actor handle, or a PrefillWorker)")

    def submit(self, tokens, start: int,
               sampling: SamplingParams) -> _PrefillJob:
        args = (list(tokens), int(start), float(sampling.temperature),
                float(sampling.top_p), int(sampling.seed))
        if self._kind == "deployment":
            ref = self._target.method("prefill").remote(*args)
            return _PrefillJob(future=ref.future())
        if self._kind == "actor":
            ref = self._target.prefill.remote(*args)
            return _PrefillJob(future=ref.future())
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="rtpu-prefill")
        return _PrefillJob(
            future=self._pool.submit(self._target.prefill, *args))


def as_prefill_client(target) -> PrefillClient:
    return target if isinstance(target, PrefillClient) \
        else PrefillClient(target)
