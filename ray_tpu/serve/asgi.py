"""ASGI ingress: serve any ASGI application (Starlette/FastAPI-shaped)
behind deployments and the HTTP proxy.

Reference: serve.ingress + the ASGI receive/send plumbing in
serve/_private/http_util.py (ASGIHTTPSender) and proxy — re-implemented
on the stdlib: the replica drives the app's ``(scope, receive, send)``
protocol with asyncio and returns a plain response dict, so the proxy and
DeploymentHandle callers stay transport-agnostic.
"""
from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict, Optional
from urllib.parse import urlsplit


class ASGIAdapter:
    """Drives one ASGI app.  ``handle(request_dict) -> response_dict``
    where request = {method, path, query_string, headers, body} and
    response = {status, headers, body}; headers travel as a LIST of
    (name, value) pairs end-to-end so duplicates (Set-Cookie) survive."""

    def __init__(self, app: Callable):
        import threading

        self.app = app
        # One persistent loop per adapter: a per-request asyncio.run would
        # pay loop setup/teardown on the serving hot path and break apps
        # holding loop-bound state (sessions, locks) across requests.
        self._loop = asyncio.new_event_loop()
        self._loop_lock = threading.Lock()

    def handle(self, request: Dict[str, Any]) -> Dict[str, Any]:
        with self._loop_lock:  # replicas may serve from several threads
            return self._loop.run_until_complete(self._run(request))

    async def _run(self, request: Dict[str, Any]) -> Dict[str, Any]:
        split = urlsplit(request.get("path", "/"))
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.get("method", "GET").upper(),
            "path": split.path or "/",
            "raw_path": (split.path or "/").encode(),
            "query_string": (request.get("query_string")
                             or split.query or "").encode()
            if isinstance(request.get("query_string", ""), str)
            else request.get("query_string", b""),
            "headers": [(k.lower().encode(), v.encode())
                        for k, v in _header_pairs(request.get("headers"))],
            "server": ("ray_tpu-serve", 0),
            "client": ("127.0.0.1", 0),
            "scheme": "http",
            "root_path": "",
        }
        body = request.get("body") or b""
        if isinstance(body, str):
            body = body.encode()
        received = {"sent": False}

        async def receive():
            if received["sent"]:
                return {"type": "http.disconnect"}
            received["sent"] = True
            return {"type": "http.request", "body": body,
                    "more_body": False}

        response = {"status": 500, "headers": [], "body": b""}
        chunks = []

        async def send(message):
            if message["type"] == "http.response.start":
                response["status"] = message["status"]
                response["headers"] = [
                    (k.decode(), v.decode())
                    for k, v in message.get("headers") or []]
            elif message["type"] == "http.response.body":
                chunks.append(message.get("body") or b"")

        await self.app(scope, receive, send)
        response["body"] = b"".join(chunks)
        return response


def _header_pairs(headers) -> list:
    """Accept either a dict or a list of (name, value) pairs."""
    if headers is None:
        return []
    if isinstance(headers, dict):
        return list(headers.items())
    return list(headers)


class _IngressCallable:
    """The replica-side callable serve.ingress deploys: builds the adapter
    once per replica, exposes the dict protocol."""

    def __init__(self, app_builder):
        if _looks_like_app(app_builder):
            app = app_builder
        elif callable(app_builder):
            app = app_builder()  # zero-arg factory (builds per replica)
        else:
            raise TypeError("ingress() wants an ASGI app or a factory")
        self._adapter = ASGIAdapter(app)

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        return self._adapter.handle(request)


def _looks_like_app(obj) -> bool:
    """ASGI apps are callables taking (scope, receive, send)."""
    import inspect

    try:
        sig = inspect.signature(obj)
        return len(sig.parameters) >= 3
    except (TypeError, ValueError):
        return False


def ingress(app, *, name: Optional[str] = None, num_replicas: int = 1,
            autoscaling_config: Optional[dict] = None):
    """Wrap an ASGI app (or zero-arg factory returning one) as a
    Deployment; the proxy routes every method under /<name>/... to it."""
    from ray_tpu.serve.api import Deployment

    dep = Deployment(_IngressCallable,
                     name or getattr(app, "__name__", "ingress"),
                     num_replicas, None, None, autoscaling_config)
    dep.bind(app)
    dep.is_ingress = True
    return dep
