"""Serve-equivalent model serving on actors.

Reference: python/ray/serve/ — control plane (ServeController reconciling
DeploymentState, serve/controller.py:60), data plane (HTTPProxy → Router →
replica actors, _private/http_proxy.py:230, router.py:221, replica.py:507).

This implementation keeps the same three planes in miniature:
- deployments: @serve.deployment + serve.run build replica actor sets,
- routing: DeploymentHandle round-robins replicas with an in-flight cap
  and queue-based backpressure,
- HTTP: a proxy actor running a threaded stdlib HTTP server (uvicorn isn't
  in the image) that forwards JSON bodies to handles.
Replica autoscaling uses the reference's formula (autoscaling_policy.py:10):
ceil(current * avg_queued / target) clamped to [min, max].
"""
from ray_tpu.serve.api import (  # noqa: F401
    Deployment,
    DeploymentHandle,
    delete,
    deployment,
    get_deployment_handle,
    run,
    shutdown,
    start_http_proxies,
    start_http_proxy,
)
from ray_tpu.serve.autoscaling import calculate_desired_num_replicas  # noqa: F401
from ray_tpu.serve.asgi import ASGIAdapter, ingress  # noqa: F401
from ray_tpu.serve.batching import batch  # noqa: F401

# Sampling params and the prefix-cache surface are import-light (no jax
# at module scope) — export them eagerly.
from ray_tpu.serve.sampling import SamplingParams  # noqa: F401
from ray_tpu.serve.prefix_cache import (  # noqa: F401
    PrefixCacheLocal,
    PrefixDirectory,
    affinity_key,
    create_directory,
)

# The LLM decode engine and prefill worker pull in jax/flax — load them
# lazily so importing ray_tpu.serve stays cheap for deployments that
# never touch a model.
_LLM_EXPORTS = ("LLMEngine", "LLMServer", "NaiveLM", "PagePool",
                "build_model", "generate_many")
_PREFILL_EXPORTS = ("PrefillWorker", "PrefillClient")


def __getattr__(name):
    if name in _LLM_EXPORTS:
        from ray_tpu.serve import llm_engine

        return getattr(llm_engine, name)
    if name in _PREFILL_EXPORTS:
        from ray_tpu.serve import prefill

        return getattr(prefill, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
