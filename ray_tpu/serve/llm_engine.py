"""Continuous-batching LLM decode engine with a paged KV cache.

The inference half of the north star: `ray_tpu/serve/` routed and
wall-clock-batched requests, but had no decode path — this module is the
replica-resident engine that turns the models we train
(`ray_tpu/models/gpt2.py`, `llama.py`) into a serving workload
(reference composition: Ray's latency-oriented serving tier over the
task/actor/object substrate, arxiv 1712.05889; engine design follows the
continuous-batching literature — Orca's iteration-level scheduling and
vLLM's paged KV cache).

Load-bearing ideas:

1. **Fixed-slot compiled decode step.**  The decode program is compiled
   ONCE for `[max_slots]`-shaped inputs (token ids, lengths, page table,
   active mask, sampling params).  Admitting or retiring a request flips
   host-side state — it never changes a traced shape, so the
   steady-state loop never recompiles.  Prefill compiles per
   power-of-two prompt bucket (bounded: log2(max_ctx) programs).

2. **Token-boundary admission.**  The engine loop runs one decode step
   for ALL in-flight requests, then admits pending requests into free
   slots *between* steps (one prefill each) — a new request joins the
   running batch at the next token boundary instead of waiting for the
   batch to drain (Orca's iteration-level scheduling).

3. **Paged KV cache.**  K/V live in fixed-size pages allocated from a
   device-resident pool (`PagePool` — the SegmentPool free-list recycle
   design from `_private/object_store.py:163`, collapsed to one size
   class because pages are uniform).  A sequence owns `ceil(len/page)`
   pages found through a per-slot page table; the decode step gathers
   pages into the attention view and scatters the new token's K/V back.
   Long and short sequences share the pool without fragmentation, pages
   recycle at retirement, and when the pool runs dry the engine preempts
   the youngest request (its pages free; it restarts later from
   prompt+generated-so-far — decode is seed-deterministic, so resumed
   output is identical and already-streamed chunks are never re-sent).

4. **Seeded sampling** (`serve/sampling.py`).  Temperature/top-p with a
   per-request seed; the token at absolute position t is always drawn
   with ``fold_in(PRNGKey(seed), t)``, so outputs are bitwise
   reproducible across runs, schedules, preemption-resume, and the
   speculative verify step.  ``temperature=0`` (default) is greedy
   argmax — the token-identity contract with the uncached reference.

5. **Speculative decoding.**  With a tiny ``draft_model``, each
   iteration runs ``spec_tokens-1`` cheap draft steps proposing tokens,
   then ONE target verify step over the `[max_slots, spec_tokens]`
   window that samples the target's token at every position
   (accept-longest-prefix).  Because sampling is position-seeded, the
   accepted stream is *bitwise* the non-speculative stream — the draft
   only changes how many tokens each target step yields.  The draft
   shares the page table (its pages are a parallel set of arrays), so
   page accounting stays single-pool.

6. **Cluster-wide prefix cache** (`serve/prefix_cache.py`).  After
   prefill, every full page's K/V is content-addressed by the blake2b
   of the token prefix that produced it, kept in a host LRU, and
   (optionally) published to the object plane via ``put_many`` +
   registered in a shared PrefixDirectory actor.  Admission looks up
   the longest cached prefix and prefills only the uncached tail
   (a cache-aware "tail prefill" program per bucket).

7. **Disaggregated prefill** (`serve/prefill.py`).  With a
   ``prefill=`` client, admissions with a long uncached tail are
   offloaded to dedicated prefill replicas: the engine reserves the
   slot + pages, the remote worker computes the tail KV and streams the
   pages back as object-plane refs (optionally int8 block-scaled via
   ``ops/collectives``), and the engine adopts them at a later token
   boundary — decode never stalls on a long prompt.

8. **Token-boundary hot weight swap** (``swap_weights``).  The RLHF
   close-the-loop primitive: new params install *between* decode steps
   — one ``device_put`` per version (params are a plain argument of
   the compiled steps, so a swap never recompiles and
   ``decode_cache_size`` stays 1), zero in-flight requests dropped.
   In-flight slots are recycled through the recompute-preemption path
   so their KV is rebuilt under the NEW weights (their already-sampled
   tokens are data and survive verbatim), every emitted token is
   stamped with the weight version it was sampled under, and the
   prefix-cache namespace folds the version in
   (``prefix_cache.versioned_namespace``) so stale pages become
   unaddressable.  Each decode/prefill step also captures the sampled
   token's **behavior logprob** (raw log-softmax — see
   ``sampling.sample_tokens_with_logprobs``), so the generation that
   serves RLHF rollouts yields the exact PPO-ratio denominator with no
   second forward pass (``rollout()`` / ``generate_rollouts``).

Request/response payloads ride the object plane zero-copy: see
``generate_many`` (client: ``put_many`` prompts → replica:
``get_many`` → decode → ``put_many`` outputs → client: ``get_many``).
"""
from __future__ import annotations

import collections
import dataclasses
import math
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.exceptions import EngineClosedError, KVPoolExhaustedError
from ray_tpu.serve.sampling import GREEDY, SamplingParams

_DEF = object()  # sentinel: constructor arg not given, consult CONFIG


def _cfg(name, given, fallback):
    if given is not _DEF and given is not None:
        return given
    try:
        from ray_tpu._private.config import CONFIG

        v = CONFIG.get(name)
        return v if v else fallback
    except Exception:
        return fallback


class PagePool:
    """Free-list allocator of fixed-size KV-cache pages.

    The SegmentPool design (`_private/object_store.py:163`) applied to
    device memory: pages are created once (the device arrays are
    allocated up front) and recycled through a free list instead of
    re-allocated, so steady-state admission costs a list pop.  Pages are
    uniform, so SegmentPool's power-of-two size classes collapse to one
    free list; the accounting (hits/misses, peak, in-use) keeps the same
    shape so the dashboard reads both pools alike.  Page 0 is the
    scratch page: masked-out lanes of the compiled scatter (inactive
    slots, prompt padding) are routed there so they can never corrupt a
    live sequence."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("PagePool needs >= 2 pages (page 0 is scratch)")
        self.capacity = num_pages - 1  # page 0 reserved
        self._free: collections.deque = collections.deque(range(1, num_pages))
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.peak_in_use = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop n pages, all-or-nothing (a partial grant would deadlock the
        grower against its own reservation)."""
        with self._lock:
            if len(self._free) < n:
                self.misses += 1
                return None
            self.hits += 1
            out = [self._free.popleft() for _ in range(n)]
            self.peak_in_use = max(self.peak_in_use, self.in_use)
            return out

    def free(self, pages: Sequence[int]):
        with self._lock:
            self._free.extend(pages)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"capacity": self.capacity, "free": len(self._free),
                    "in_use": self.in_use, "peak_in_use": self.peak_in_use,
                    "hits": self.hits, "misses": self.misses}


@dataclasses.dataclass
class _Request:
    id: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int]
    sampling: SamplingParams = GREEDY
    submitted: float = dataclasses.field(default_factory=time.monotonic)
    out: List[int] = dataclasses.field(default_factory=list)
    chunks: "queue.Queue" = dataclasses.field(default_factory=queue.Queue)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    error: Optional[BaseException] = None
    streamed: int = 0  # tokens already pushed to the chunk stream
    admit_seq: int = -1  # preemption picks the youngest (highest seq)
    # Consumption mark: True once the caller has the terminal state
    # (result() returned / raised, or the None chunk was delivered).
    # The registry's size bound only evicts consumed requests — evicting
    # a finished-but-undrained streaming request would silently lose its
    # tail chunks.
    consumed: bool = False
    spec_proposed: int = 0
    spec_accepted: int = 0
    # Parallel to ``out``: the behavior logprob of each emitted token
    # (raw log-softmax at the chosen token) and the weight version it
    # was sampled under (swap_weights bumps the engine version).
    out_logps: List[float] = dataclasses.field(default_factory=list)
    out_versions: List[int] = dataclasses.field(default_factory=list)
    # Distributed trace the request was submitted under (the caller's
    # (trace_id, span_id) pair); engine step spans stamp it so a serve
    # request's decode steps land in the client's timeline.
    trace_ctx: Optional[tuple] = None

    def context(self) -> List[int]:
        """Prompt plus generated-so-far — what a (re)admission prefills.
        Decode is seed-deterministic, so a preempted request resumed
        from this context produces exactly the tokens it would have."""
        return self.prompt + self.out

    def finish(self, error: Optional[BaseException] = None):
        self.error = error
        if self.streamed < len(self.out):
            self.chunks.put(self.out[self.streamed:])
            self.streamed = len(self.out)
        self.chunks.put(None)
        self.done.set()


class LLMEngine:
    """Replica-resident continuous-batching decode engine.

    ``submit()`` is thread-safe and returns immediately; a background
    flow.Stage (sink mode) owns all device state and serializes
    prefill/decode.  ``result()`` blocks for the full output,
    ``stream()`` yields token chunks as they are produced (chunks
    arrive while the request is still decoding).  Default sampling is
    greedy (argmax) — the token-identity contract with the uncached
    reference is what the correctness gates assert; per-request
    temperature/top-p/seed turn on real (still deterministic)
    sampling."""

    # Registry size bound: evict CONSUMED finished requests past LIMIT,
    # down to FLOOR (a long-lived replica must not leak one _Request per
    # call, but an undrained streaming request is never dropped).
    REGISTRY_LIMIT = 4096
    REGISTRY_FLOOR = 2048

    def __init__(self, model, params, *, max_slots=_DEF, page_size=_DEF,
                 num_pages: Optional[int] = None,
                 max_ctx: Optional[int] = None,
                 chunk_tokens: int = 8, start: bool = True,
                 draft_model=None, draft_params=None, spec_tokens=_DEF,
                 draft_window: Optional[int] = None,
                 prefix_cache=None, cache_namespace: str = "",
                 prefix_directory=None, directory_timeout_s: float = 5.0,
                 prefill=None, prefill_min_tokens=_DEF):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self._model = model
        self._params = params
        c = model.config
        self.num_layers = c.num_layers
        self.head_dim = c.head_dim
        self.kv_heads = getattr(c, "num_kv_heads", c.num_heads)
        self.dtype = c.dtype
        self.max_slots = int(_cfg("serve_max_slots", max_slots, 8))
        self.page_size = int(_cfg("serve_page_size", page_size, 16))
        self.max_ctx = int(max_ctx or c.max_position_embeddings)
        self.pages_per_slot = math.ceil(self.max_ctx / self.page_size)
        self.max_ctx = self.pages_per_slot * self.page_size
        if self.max_ctx > c.max_position_embeddings:
            raise ValueError(
                f"max_ctx {self.max_ctx} (page-rounded) exceeds the model's "
                f"max_position_embeddings {c.max_position_embeddings}")
        # Default pool: full provisioning (+1 scratch) — every slot can
        # reach max_ctx, preemption never fires.  Size it down to share
        # the pool across more slots than worst-case memory allows.
        if num_pages is None:
            num_pages = self.max_slots * self.pages_per_slot + 1
        self.pool = PagePool(num_pages)
        self.chunk_tokens = chunk_tokens

        shape = (self.num_layers, num_pages, self.page_size,
                 self.kv_heads, self.head_dim)
        self._k_pages = jnp.zeros(shape, self.dtype)
        self._v_pages = jnp.zeros(shape, self.dtype)

        # ---- speculative decoding (draft + verify) ----
        self.spec_tokens = int(_cfg("serve_spec_tokens", spec_tokens,
                                    4 if draft_model is not None else 0))
        self._draft_model = draft_model
        self._draft_params = draft_params
        self._spec = draft_model is not None and self.spec_tokens >= 2
        if draft_model is not None and not self._spec:
            raise ValueError(
                f"speculative decoding needs spec_tokens >= 2, got "
                f"{self.spec_tokens}")
        if self._spec:
            dc = draft_model.config
            if dc.vocab_size != c.vocab_size or \
                    dc.max_position_embeddings < self.max_ctx:
                raise ValueError(
                    "draft model must share the target's vocab and cover "
                    "its max_ctx "
                    f"(draft vocab {dc.vocab_size} vs {c.vocab_size}, "
                    f"positions {dc.max_position_embeddings} vs "
                    f"{self.max_ctx})")
            dshape = (dc.num_layers, num_pages, self.page_size,
                      getattr(dc, "num_kv_heads", dc.num_heads), dc.head_dim)
            self._dk_pages = jnp.zeros(dshape, dc.dtype)
            self._dv_pages = jnp.zeros(dshape, dc.dtype)
        # Sliding-window draft attention: the draft's page gather — the
        # dominant per-step cost at long context — shrinks from
        # pages_per_slot to ceil(draft_window / page_size) pages.
        self._draft_window_pages = None
        if draft_window is not None:
            if not self._spec:
                raise ValueError("draft_window needs a draft model")
            self._draft_window_pages = max(
                2, math.ceil(int(draft_window) / self.page_size))

        # ---- prefix cache ----
        from ray_tpu.serve import prefix_cache as pc

        if prefix_cache is True:
            prefix_cache = pc.PrefixCacheLocal(
                int(_cfg("serve_prefix_cache_bytes", _DEF,
                         256 * 1024 * 1024)))
        self._prefix = prefix_cache or None
        self._directory = prefix_directory
        self._directory_timeout = float(directory_timeout_s)
        if not cache_namespace:
            cache_namespace = (f"{type(model).__name__}|{c!r}|"
                               f"ps{self.page_size}")
        # The engine owns version-folding: callers pass the UNVERSIONED
        # base namespace and every swap_weights re-derives the effective
        # namespace, making pre-swap pages unaddressable (see
        # prefix_cache.versioned_namespace).
        self._base_namespace = cache_namespace
        self._weight_version = 0
        self._namespace = pc.versioned_namespace(cache_namespace, 0)
        # Refs for pages this replica published: keeps the object alive
        # across the publish handoff even if the directory is slow to
        # pin; bounded (the directory is the durable holder).
        self._published_refs: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()

        # ---- disaggregated prefill ----
        self._prefill_min = int(_cfg("serve_prefill_min_tokens",
                                     prefill_min_tokens, 32))
        self._prefill_client = None
        if prefill is not None:
            from ray_tpu.serve.prefill import as_prefill_client

            self._prefill_client = as_prefill_client(prefill)
        # (req, job, start_tokens) awaiting remote KV — NOTHING is
        # reserved while a prefill is in flight (a held slot would
        # starve interactive admissions behind a long-prompt burst);
        # completed payloads park in _ready until a slot frees.
        self._awaiting: List[tuple] = []
        self._ready: collections.deque = collections.deque()
        self._prefill_max_inflight = 2 * self.max_slots

        # Host-side slot state (the loop thread is the only writer).
        self._table = np.zeros((self.max_slots, self.pages_per_slot),
                               np.int32)
        self._lengths = np.zeros((self.max_slots,), np.int32)
        self._active = np.zeros((self.max_slots,), bool)
        self._last_tok = np.zeros((self.max_slots,), np.int32)
        self._temps = np.zeros((self.max_slots,), np.float32)
        self._top_ps = np.ones((self.max_slots,), np.float32)
        self._seeds = np.zeros((self.max_slots,), np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(self.max_slots)]
        self._slot_req: Dict[int, _Request] = {}

        self._decode = jax.jit(self._make_decode_step(model),
                               donate_argnums=(1, 2))
        if self._spec:
            self._draft_decode = jax.jit(
                self._make_decode_step(
                    draft_model, window_pages=self._draft_window_pages),
                donate_argnums=(1, 2))
            self._verify = jax.jit(self._make_verify_step(model),
                                   donate_argnums=(1, 2))
        self._adopt = jax.jit(self._make_adopt(self.dtype),
                              donate_argnums=(0, 1))
        self._adopt_buf_k = np.zeros(
            (self.num_layers, self.pages_per_slot, self.page_size,
             self.kv_heads, self.head_dim), np.float32)
        self._adopt_buf_v = np.zeros_like(self._adopt_buf_k)
        self._prefills: Dict[Any, Any] = {}

        self._pending: collections.deque = collections.deque()
        self._requests: Dict[int, _Request] = {}
        self._next_id = 0
        self._admit_counter = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._stats = collections.Counter()
        self._occupancy_sum = 0.0
        self._t0 = time.monotonic()
        # Hot weight swap: queued (params_or_ref, version, event) applied
        # by the loop thread at the next token boundary.
        self._pending_swaps: collections.deque = collections.deque()
        self._swap_latency_sum = 0.0
        # Generation-plane accounting for the RLHF overlap gates: wall
        # time spent doing device work (prefill/decode/swap) and the
        # completion stamp of recent decode steps.
        self._work_s = 0.0
        self._step_stamps: collections.deque = collections.deque(
            maxlen=1024)
        self._metrics = None
        self._metrics_flush = 0.0
        self._stage = None
        if start:
            # The engine loop is a sink stage on the async dataflow
            # substrate: the tick source runs until the stage's token
            # cancels, one fn call per engine iteration, and close()
            # joins the worker thread through the substrate.
            from ray_tpu.parallel import flow

            self._stage = flow.Stage(
                self._tick_source(), self._iteration, sink=True, workers=1,
                name="llm_engine", export_metrics=False)

    # ------------------------------------------------------------------
    # public API (any thread)
    # ------------------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               sampling: Optional[SamplingParams] = None,
               temperature: Optional[float] = None,
               top_p: Optional[float] = None,
               seed: Optional[int] = None) -> int:
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.max_ctx:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_ctx {self.max_ctx}")
        if sampling is None:
            sampling = SamplingParams(
                temperature=0.0 if temperature is None else float(temperature),
                top_p=1.0 if top_p is None else float(top_p),
                seed=0 if seed is None else int(seed))
        sampling.validate()
        trace_ctx = None
        try:
            from ray_tpu import observability as obs

            if obs.enabled():
                trace_ctx = obs.get_context()
        except Exception:
            pass
        with self._cond:
            if self._closed:
                raise EngineClosedError("engine is closed")
            rid = self._next_id
            self._next_id += 1
            req = _Request(rid, prompt, max_new_tokens, eos_id,
                           sampling=sampling, trace_ctx=trace_ctx)
            self._requests[rid] = req
            self._pending.append(req)
            self._cond.notify_all()
        return rid

    def result(self, rid: int, timeout: Optional[float] = None) -> List[int]:
        req = self._requests[rid]
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {rid} not done within {timeout}s")
        req.consumed = True
        if req.error is not None:
            raise req.error
        return list(req.out)

    def swap_weights(self, params, version: int,
                     timeout: Optional[float] = 60.0) -> int:
        """Install new model params at the next token boundary (hot swap).

        ``params`` is either a host/device param pytree or an
        ``ObjectRef`` from the versioned one-put weight broadcast (the
        learner ``put``s once; every replica resolves the same ref) —
        either way the engine pays exactly ONE ``device_put`` per
        version.  The compiled decode/prefill/verify programs take
        params as a plain argument, so a swap never recompiles
        (``decode_cache_size`` stays 1) and no in-flight request is
        dropped: active slots are recycled through the
        recompute-preemption path, which re-prefills their
        prompt+generated-so-far context under the NEW weights — their
        already-emitted tokens (and captured logprobs/version stamps)
        are data and survive verbatim, and every later token is sampled
        under, and stamped with, ``version``.  The prefix-cache
        namespace re-derives with the new version, so pre-swap KV pages
        can never be adopted into post-swap contexts.

        ``version`` must be strictly greater than the current engine
        version (stamps must be unambiguous).  With ``timeout`` the call
        blocks until the loop applies the swap (raises ``TimeoutError``
        otherwise); ``timeout=None`` returns immediately.  Returns the
        installed version."""
        version = int(version)
        applied = threading.Event()
        with self._cond:
            if self._closed:
                raise EngineClosedError("engine is closed")
            pending_max = max(
                [v for _, v, _ in self._pending_swaps],
                default=self._weight_version)
            if version <= pending_max:
                raise ValueError(
                    f"swap version {version} must exceed the current "
                    f"version {pending_max}")
            self._pending_swaps.append((params, version, applied))
            self._cond.notify_all()
        if timeout is not None:
            if not applied.wait(timeout):
                raise TimeoutError(
                    f"weight swap to version {version} not applied within "
                    f"{timeout}s")
            if self._weight_version < version:
                # close()/_fail_all wakes waiters without applying.
                raise EngineClosedError(
                    f"engine closed before swap to version {version} "
                    f"applied")
        return version

    @property
    def weight_version(self) -> int:
        return self._weight_version

    def rollout(self, rid: int, timeout: Optional[float] = None
                ) -> Dict[str, Any]:
        """Blocking full result PLUS the per-token behavior logprobs and
        weight-version stamps — the RLHF rollout record (no second
        forward pass needed for the PPO ratio)."""
        req = self._requests[rid]
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {rid} not done within {timeout}s")
        req.consumed = True
        if req.error is not None:
            raise req.error
        return {
            "prompt": list(req.prompt),
            "tokens": list(req.out),
            "logprobs": list(req.out_logps),
            "versions": list(req.out_versions),
        }

    def generate_rollouts(self, prompts: Sequence[Sequence[int]],
                          max_new_tokens: int = 16,
                          eos_id: Optional[int] = None,
                          sampling: Optional[List[SamplingParams]] = None,
                          timeout: float = 300.0) -> List[Dict[str, Any]]:
        """Submit a prompt batch and collect version-stamped rollouts —
        continuous batching amortizes the decode across the whole batch
        (all prompts are in flight together, subject to ``max_slots``)."""
        if sampling is None:
            sampling = [None] * len(prompts)
        rids = [self.submit(p, max_new_tokens, eos_id, sampling=s)
                for p, s in zip(prompts, sampling)]
        return [self.rollout(r, timeout=timeout) for r in rids]

    def recent_step_stamps(self) -> List[float]:
        """``time.monotonic()`` completion stamps of recent decode steps
        — the overlap gates prove generation ran inside an SGD window by
        finding stamps inside it."""
        with self._lock:
            return list(self._step_stamps)

    def stream(self, rid: int, timeout: float = 120.0):
        """Yield token chunks (lists) as they are produced; returns when
        the request retires.  Raises the request's error, if any."""
        req = self._requests[rid]
        while True:
            chunk = req.chunks.get(timeout=timeout)
            if chunk is None:
                break
            yield chunk
        req.consumed = True
        if req.error is not None:
            raise req.error

    def request_stats(self, rid: int) -> Dict[str, Any]:
        """Per-request accounting (speculative acceptance metrics)."""
        req = self._requests[rid]
        return {
            "tokens": len(req.out),
            "spec_proposed": req.spec_proposed,
            "spec_accepted": req.spec_accepted,
            "spec_acceptance_rate": (req.spec_accepted / req.spec_proposed
                                     if req.spec_proposed else 0.0),
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n_active = int(self._active.sum())
            s = dict(self._stats)
            n_awaiting = len(self._awaiting) + len(self._ready)
        pool = self.pool.stats()
        steps = s.get("steps", 0)
        out = {
            "active": n_active,
            "pending": len(self._pending),
            "admitted": s.get("admitted", 0),
            "admitted_mid_batch": s.get("admitted_mid_batch", 0),
            "completed": s.get("completed", 0),
            "preemptions": s.get("preemptions", 0),
            "steps": steps,
            "tokens_generated": s.get("tokens", 0),
            "avg_batch_occupancy": (self._occupancy_sum / steps
                                    if steps else 0.0),
            "pages_in_use": pool["in_use"],
            "pages_free": pool["free"],
            "page_pool": pool,
            "prefill_buckets": len(self._prefills),
            # sampling / speculative decoding
            "spec_steps": s.get("spec_steps", 0),
            "spec_proposed": s.get("spec_proposed", 0),
            "spec_accepted": s.get("spec_accepted", 0),
            "spec_acceptance_rate": (
                s.get("spec_accepted", 0) / s.get("spec_proposed", 1)
                if s.get("spec_proposed", 0) else 0.0),
            # prefix cache
            "prefix_hit_pages": s.get("prefix_hit_pages", 0),
            "prefix_remote_hit_pages": s.get("prefix_remote_hit_pages", 0),
            "prefix_published_pages": s.get("prefix_published_pages", 0),
            "prefill_tokens": s.get("prefill_tokens", 0),
            "prefill_tokens_saved": s.get("prefill_tokens_saved", 0),
            # disaggregated prefill
            "prefill_offloaded": s.get("prefill_offloaded", 0),
            "prefill_inflight": n_awaiting,
            "prefill_prefix_fallback": s.get("prefill_prefix_fallback", 0),
            "wire_bytes": s.get("wire_bytes", 0),
            "wire_fp32_bytes": s.get("wire_fp32_bytes", 0),
            # hot weight swap / generation-plane accounting
            "weight_version": self._weight_version,
            "swaps": s.get("swaps", 0),
            "swap_reprefills": s.get("swap_reprefills", 0),
            "swap_latency_s_avg": (self._swap_latency_sum / s["swaps"]
                                   if s.get("swaps", 0) else 0.0),
            "work_seconds": self._work_s,
        }
        if self._prefix is not None:
            out["prefix_cache"] = self._prefix.stats()
        cache_size = getattr(self._decode, "_cache_size", None)
        if callable(cache_size):
            out["decode_cache_size"] = cache_size()
        return out

    def close(self, timeout: float = 10.0):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            swaps = list(self._pending_swaps)
            self._pending_swaps.clear()
            self._cond.notify_all()
        for _, _, applied in swaps:
            applied.set()  # wake blocked swappers; version stays put
        if self._stage is not None:
            self._stage.close()
        err = EngineClosedError("engine closed with requests in flight")
        for req in list(self._requests.values()):
            if not req.done.is_set():
                req.finish(error=err)

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _gather_for(self, cfg):
        """Pages + [slots, pp] table → per-slot contiguous
        [L, slots, max_ctx, Hkv, D] attention view (rows past each
        slot's length are garbage — masked by cached_attention)."""
        L = cfg.num_layers
        hkv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        d, mc = cfg.head_dim, self.max_ctx

        def gather(pages, table):
            g = pages[:, table]  # [L, slots, pp, ps, Hkv, D]
            return g.reshape(L, table.shape[0], mc, hkv, d)

        return gather

    def _make_decode_step(self, model, window_pages: Optional[int] = None):
        """One token for every slot (fixed shapes — compiled once).
        Inactive lanes compute garbage routed to the scratch page.
        Shared shape for the target and the draft model (each gets its
        own jit over its own page arrays).

        ``window_pages`` (draft only) switches the attention view to a
        sliding window of the LAST n pages: the page gather — the
        step's dominant cost at long context — shrinks from
        pages_per_slot to n.  Positional information is baked into the
        cached K/V at write time (learned embeddings at embed, rope at
        projection), so a windowed view plus window-relative valid
        lengths is exact windowed attention, no re-indexing.  The
        target never does this (it must attend to everything); the
        draft is a guesser, and the verify step catches what the
        shortened horizon loses."""
        jnp = self._jnp
        cfg = model.config
        L, ps, pp = cfg.num_layers, self.page_size, self.pages_per_slot
        from ray_tpu.serve.sampling import sample_tokens_with_logprobs

        hkv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        if window_pages is None or window_pages >= pp:
            gather = self._gather_for(cfg)

            def gather_view(pages, table, lengths):
                return gather(pages, table), lengths
        else:
            wp = int(window_pages)

            def gather_view(pages, table, lengths):
                # Pages [(len-1)//ps - wp + 1 .. (len-1)//ps], clamped:
                # the newest wp pages.  Valid rows within the view are
                # lengths - start*ps (window-relative).
                last_page = jnp.maximum(lengths - 1, 0) // ps
                start = jnp.maximum(last_page - (wp - 1), 0)
                cols = start[:, None] + jnp.arange(wp)[None]
                idx = jnp.take_along_axis(
                    table, jnp.minimum(cols, pp - 1), axis=1)
                g = pages[:, idx]  # [L, slots, wp, ps, Hkv, D]
                view = g.reshape(L, table.shape[0], wp * ps, hkv,
                                 cfg.head_dim)
                return view, lengths - start * ps

        def step(params, k_pages, v_pages, table, lengths, tokens, active,
                 temps, top_ps, seeds):
            k_cache, view_len = gather_view(k_pages, table, lengths)
            v_cache, _ = gather_view(v_pages, table, lengths)
            kv = [(k_cache[i], v_cache[i]) for i in range(L)]
            logits, new_kvs = model.apply(
                {"params": params}, tokens[:, None], lengths[:, None], kv,
                view_len)
            # The generated token sits at absolute position lengths + 1.
            next_tok, next_logp = sample_tokens_with_logprobs(
                logits[:, -1], lengths + 1, temps, top_ps, seeds)
            newk = jnp.stack([nk[0][:, 0] for nk in new_kvs])
            newv = jnp.stack([nk[1][:, 0] for nk in new_kvs])
            slot_ix = jnp.arange(table.shape[0])
            page_col = jnp.minimum(lengths // ps, pp - 1)
            page_idx = jnp.where(active, table[slot_ix, page_col], 0)
            off = lengths % ps
            k_pages = k_pages.at[:, page_idx, off].set(
                newk.astype(k_pages.dtype))
            v_pages = v_pages.at[:, page_idx, off].set(
                newv.astype(v_pages.dtype))
            return k_pages, v_pages, next_tok, next_logp

        return step

    def _make_verify_step(self, model):
        """Target-model verification of a [slots, k] speculative window:
        one forward over the window, KV scattered for every position,
        and the target's sampled token at every position — the host
        applies accept-longest-prefix to the result."""
        jnp = self._jnp
        cfg = model.config
        L, ps, pp = cfg.num_layers, self.page_size, self.pages_per_slot
        k_win = self.spec_tokens
        gather = self._gather_for(cfg)
        from ray_tpu.serve.sampling import sample_tokens_with_logprobs

        def verify(params, k_pages, v_pages, table, lengths, window, active,
                   temps, top_ps, seeds):
            k_cache = gather(k_pages, table)
            v_cache = gather(v_pages, table)
            kv = [(k_cache[i], v_cache[i]) for i in range(L)]
            positions = lengths[:, None] + jnp.arange(k_win)[None]
            logits, new_kvs = model.apply(
                {"params": params}, window, positions, kv, lengths)
            newk = jnp.stack([nk[0] for nk in new_kvs])  # [L,slots,k,Hkv,D]
            newv = jnp.stack([nk[1] for nk in new_kvs])
            page_col = jnp.minimum(positions // ps, pp - 1)
            page_idx = jnp.where(active[:, None],
                                 jnp.take_along_axis(table, page_col, axis=1),
                                 0)
            off = positions % ps
            k_pages = k_pages.at[:, page_idx, off].set(
                newk.astype(k_pages.dtype))
            v_pages = v_pages.at[:, page_idx, off].set(
                newv.astype(v_pages.dtype))
            n = table.shape[0]
            flat = logits.reshape(n * k_win, -1)
            rep = lambda a: jnp.repeat(a, k_win)
            sampled, logps = sample_tokens_with_logprobs(
                flat, (positions + 1).reshape(-1), rep(temps), rep(top_ps),
                rep(seeds))
            return (k_pages, v_pages, sampled.reshape(n, k_win),
                    logps.reshape(n, k_win))

        return verify

    def _make_adopt(self, dtype):
        """Scatter host-staged KV pages (prefix-cache hits, disaggregated
        prefill payloads) into the device page arrays.  Fixed
        [pages_per_slot] shape — compiled once; unused rows are routed
        to the scratch page by the host-masked ids."""

        def adopt(k_pages, v_pages, page_ids, k_new, v_new):
            k_pages = k_pages.at[:, page_ids].set(
                k_new.astype(k_pages.dtype))
            v_pages = v_pages.at[:, page_ids].set(
                v_new.astype(v_pages.dtype))
            return k_pages, v_pages

        return adopt

    def _prefill_fn(self, bucket: int):
        """Full-context prefill (empty cache): one program per pow2
        bucket."""
        key = ("full", bucket)
        fn = self._prefills.get(key)
        if fn is not None:
            return fn
        jax, jnp = self._jax, self._jnp
        model = self._model
        L, ps = self.num_layers, self.page_size
        from ray_tpu.serve.sampling import sample_tokens_with_logprobs

        def prefill(params, k_pages, v_pages, row, tokens, p, temp, top_p,
                    seed):
            """tokens: [bucket] ids padded past p; row: [pp] page table
            row.  Returns updated pages + the sampled next token (the
            token at absolute position p, key fold_in(seed, p)) and its
            behavior logprob."""
            ids = tokens[None]
            positions = jnp.arange(bucket)[None]
            empty = [(jnp.zeros((1, 0, self.kv_heads, self.head_dim),
                                self.dtype),) * 2 for _ in range(L)]
            logits, new_kvs = model.apply(
                {"params": params}, ids, positions, empty,
                jnp.zeros((1,), jnp.int32))
            toks, logps = sample_tokens_with_logprobs(
                logits[0, p - 1][None], jnp.reshape(p, (1,)),
                jnp.reshape(temp, (1,)), jnp.reshape(top_p, (1,)),
                jnp.reshape(seed, (1,)))
            next_tok, next_logp = toks[0], logps[0]
            t = jnp.arange(bucket)
            page_idx = jnp.where(t < p, row[t // ps], 0)
            off = t % ps
            newk = jnp.stack([nk[0][0] for nk in new_kvs])  # [L,bkt,Hkv,D]
            newv = jnp.stack([nk[1][0] for nk in new_kvs])
            k_pages = k_pages.at[:, page_idx, off].set(
                newk.astype(self.dtype))
            v_pages = v_pages.at[:, page_idx, off].set(
                newv.astype(self.dtype))
            return k_pages, v_pages, next_tok, next_logp

        fn = jax.jit(prefill, donate_argnums=(1, 2))
        self._prefills[key] = fn
        return fn

    def _tail_prefill_fn(self, bucket: int):
        """Cache-aware tail prefill: the first ``start`` tokens' KV is
        already in the slot's pages (adopted from the prefix cache), so
        only the tail runs through the model — the tail tokens attend to
        the gathered cache prefix plus themselves.  One program per pow2
        tail bucket."""
        key = ("tail", bucket)
        fn = self._prefills.get(key)
        if fn is not None:
            return fn
        jax, jnp = self._jax, self._jnp
        model = self._model
        L, ps, pp = self.num_layers, self.page_size, self.pages_per_slot
        gather = self._gather_for(model.config)
        from ray_tpu.serve.sampling import sample_tokens_with_logprobs

        def tail_prefill(params, k_pages, v_pages, row, tokens, start, p,
                         temp, top_p, seed):
            """tokens: [bucket] tail ids (absolute positions start..p-1)
            padded past p-start; returns updated pages + the sampled
            next token at absolute position p and its behavior logprob."""
            k_cache = gather(k_pages, row[None])  # [L, 1, max_ctx, Hkv, D]
            v_cache = gather(v_pages, row[None])
            kv = [(k_cache[i], v_cache[i]) for i in range(L)]
            positions = (start + jnp.arange(bucket))[None]
            logits, new_kvs = model.apply(
                {"params": params}, tokens[None], positions, kv,
                jnp.reshape(start, (1,)))
            tail_len = p - start
            toks, logps = sample_tokens_with_logprobs(
                logits[0, tail_len - 1][None], jnp.reshape(p, (1,)),
                jnp.reshape(temp, (1,)), jnp.reshape(top_p, (1,)),
                jnp.reshape(seed, (1,)))
            next_tok, next_logp = toks[0], logps[0]
            t = jnp.arange(bucket)
            abs_pos = start + t
            page_idx = jnp.where(
                t < tail_len, row[jnp.minimum(abs_pos // ps, pp - 1)], 0)
            off = abs_pos % ps
            newk = jnp.stack([nk[0][0] for nk in new_kvs])
            newv = jnp.stack([nk[1][0] for nk in new_kvs])
            k_pages = k_pages.at[:, page_idx, off].set(
                newk.astype(self.dtype))
            v_pages = v_pages.at[:, page_idx, off].set(
                newv.astype(self.dtype))
            return k_pages, v_pages, next_tok, next_logp

        fn = jax.jit(tail_prefill, donate_argnums=(1, 2))
        self._prefills[key] = fn
        return fn

    def _draft_prefill_fn(self, bucket: int):
        """Draft-model full prefill (KV only, no sampling): in spec mode
        every admission warms the draft cache for the whole context —
        the draft is tiny by construction, so this is the cheap price of
        keeping the prefix cache and the KV wire draft-agnostic."""
        key = ("draft", bucket)
        fn = self._prefills.get(key)
        if fn is not None:
            return fn
        jax, jnp = self._jax, self._jnp
        model = self._draft_model
        dc = model.config
        L, ps = dc.num_layers, self.page_size
        hkv = getattr(dc, "num_kv_heads", dc.num_heads)

        def prefill(params, k_pages, v_pages, row, tokens, p):
            ids = tokens[None]
            positions = jnp.arange(bucket)[None]
            empty = [(jnp.zeros((1, 0, hkv, dc.head_dim), dc.dtype),) * 2
                     for _ in range(L)]
            _, new_kvs = model.apply(
                {"params": params}, ids, positions, empty,
                jnp.zeros((1,), jnp.int32))
            t = jnp.arange(bucket)
            page_idx = jnp.where(t < p, row[t // ps], 0)
            off = t % ps
            newk = jnp.stack([nk[0][0] for nk in new_kvs])
            newv = jnp.stack([nk[1][0] for nk in new_kvs])
            k_pages = k_pages.at[:, page_idx, off].set(
                newk.astype(k_pages.dtype))
            v_pages = v_pages.at[:, page_idx, off].set(
                newv.astype(v_pages.dtype))
            return k_pages, v_pages

        fn = jax.jit(prefill, donate_argnums=(1, 2))
        self._prefills[key] = fn
        return fn

    def _bucket_for(self, p: int) -> int:
        b = 8
        while b < p:
            b <<= 1
        return min(b, self.max_ctx)

    # ------------------------------------------------------------------
    # engine loop (one flow.Stage sink worker owns the device state)
    # ------------------------------------------------------------------
    def _tick_source(self):
        while True:
            with self._cond:
                if self._closed:
                    return
            if self._stage is not None and self._stage.token.cancelled:
                return
            yield None

    def _iteration(self, _tick):
        with self._cond:
            while (not self._closed and not self._pending
                   and not self._awaiting and not self._ready
                   and not self._pending_swaps
                   and not self._active.any()):
                self._cond.wait(0.2)
                if self._stage is not None and self._stage.token.cancelled:
                    return
            if self._closed:
                return
        t_work0 = time.perf_counter()
        try:
            self._apply_swaps()  # token boundary: between decode steps
            self._poll_prefill()
            self._admit()
            self._grow()
            if self._active.any():
                if self._spec:
                    self._decode_once_spec()
                else:
                    self._decode_once()
                self._step_stamps.append(time.monotonic())
        except BaseException as e:  # noqa: BLE001 — fail loudly per req
            self._fail_all(e)
            return
        t_work1 = time.perf_counter()
        self._work_s += t_work1 - t_work0
        self._record_step_span(t_work0, t_work1)
        self._flush_metrics()

    def _record_step_span(self, t0: float, t1: float) -> None:
        """Stamp the engine iteration onto an active request's trace so a
        serve request's decode steps assemble into the client's timeline.
        Free when no in-flight request carries a context."""
        ctx = None
        for req in self._slot_req.values():
            if req.trace_ctx is not None:
                ctx = tuple(req.trace_ctx)
                break
        if ctx is None:
            return
        from ray_tpu._private import profiling

        profiling.record_span("serve_engine_step", t0, t1,
                              active=int(self._active.sum()),
                              _trace_ctx=ctx)

    # ------------------------------------------------------------------
    # hot weight swap (loop thread only)
    # ------------------------------------------------------------------
    def _apply_swaps(self):
        """Install every queued weight version, newest last.  Runs
        between decode steps — the definition of a token boundary."""
        while True:
            with self._lock:
                if not self._pending_swaps:
                    return
                params, version, applied = self._pending_swaps.popleft()
            t0 = time.monotonic()
            try:
                params = self._resolve_swap_params(params)
                self._check_swap_tree(params)
            except BaseException:
                # The loop is about to die (_fail_all); wake the blocked
                # swapper NOW — its version check converts the wake into
                # a typed EngineClosedError instead of a full timeout.
                applied.set()
                raise
            # ONE device_put per version; the old arrays free once the
            # next compiled call stops referencing them.
            self._params = self._jax.device_put(params)
            self._weight_version = int(version)
            from ray_tpu.serve import prefix_cache as pc

            self._namespace = pc.versioned_namespace(
                self._base_namespace, self._weight_version)
            # In-flight requests: recycle through recompute preemption so
            # their KV is rebuilt under the new weights at re-admission
            # (sampled tokens are data; seeded sampling is position-
            # keyed, so the resumed stream continues seamlessly).
            for slot in range(self.max_slots):
                if self._active[slot]:
                    self._preempt(slot)
                    self._stats["swap_reprefills"] += 1
            self._stats["swaps"] += 1
            self._swap_latency_sum += time.monotonic() - t0
            applied.set()

    def _resolve_swap_params(self, params):
        try:
            import ray_tpu

            if isinstance(params, ray_tpu.ObjectRef):
                return ray_tpu.get(params)
        except Exception:
            pass
        return params

    def _check_swap_tree(self, params):
        """A silently mismatched tree would recompile the decode step
        (breaking the decode_cache_size==1 contract) or garble the
        model — fail loudly instead."""
        jax = self._jax
        new_leaves = jax.tree_util.tree_structure(params)
        cur_leaves = jax.tree_util.tree_structure(self._params)
        if new_leaves != cur_leaves:
            raise ValueError(
                "swap_weights params tree does not match the serving "
                f"model's ({new_leaves} vs {cur_leaves})")
        for new, cur in zip(jax.tree_util.tree_leaves(params),
                            jax.tree_util.tree_leaves(self._params)):
            if tuple(new.shape) != tuple(cur.shape) or \
                    new.dtype != cur.dtype:
                raise ValueError(
                    f"swap_weights leaf mismatch: {new.shape}/{new.dtype} "
                    f"vs serving {cur.shape}/{cur.dtype} — a swap must "
                    "not change shapes or dtypes (it would recompile)")

    def _fail_all(self, e: BaseException):
        with self._lock:
            self._closed = True  # a dead loop must reject new submits
            self._awaiting = []
            self._ready.clear()
            swaps = list(self._pending_swaps)
            self._pending_swaps.clear()
        for _, _, applied in swaps:
            applied.set()
        for req in list(self._requests.values()):
            if not req.done.is_set():
                req.finish(error=e)
        for s in range(self.max_slots):
            if self._slot_pages[s]:
                self.pool.free(self._slot_pages[s])
                self._slot_pages[s] = []
        self._active[:] = False

    # ------------------------------------------------------------------
    # admission: prefix-cache lookup, local prefill or remote offload
    # ------------------------------------------------------------------
    def _admit(self):
        """Token-boundary admission: activate completed remote prefills
        first, then fill free slots from the pending queue, one prefill
        each.  Requires prompt pages + 1 free so the first decode token
        can't immediately force a preemption.  Offload decisions happen
        BEFORE any slot or page is reserved — a long-prompt burst
        streams out to the prefill replicas immediately and interactive
        requests behind it admit without waiting."""
        self._activate_ready()
        while True:
            with self._lock:
                if not self._pending:
                    return
                req = self._pending[0]
                ctx = req.context()
                p = len(ctx)
                need = math.ceil(p / self.page_size)
                if need + 1 > self.pool.capacity:
                    # Can never fit, even with the whole pool to itself —
                    # waiting would busy-spin forever.
                    self._pending.popleft()
                    req.finish(error=KVPoolExhaustedError(
                        f"request {req.id} needs {need + 1} pages but the "
                        f"pool holds {self.pool.capacity}"))
                    continue
                inflight = len(self._awaiting) + len(self._ready)
            if (self._prefill_client is not None
                    and inflight < self._prefill_max_inflight):
                # Uncached tail from the LOCAL cache view only (a
                # directory round trip at submit time would serialize
                # admissions; remote hits engage at activation).
                start = self._local_prefix_run(ctx)
                if p - start >= self._prefill_min:
                    job = self._prefill_client.submit(ctx, start,
                                                      req.sampling)
                    with self._lock:
                        self._pending.popleft()
                        self._awaiting.append((req, job, start))
                    self._stats["prefill_offloaded"] += 1
                    continue
            with self._lock:
                free = [s for s in range(self.max_slots)
                        if not self._active[s]]
                if not free:
                    return
                pages = self.pool.alloc(need + 1)
                if pages is None:
                    return  # pool too tight right now; retry next boundary
                self.pool.free(pages[need:])  # only reserve the +1 headroom
                pages = pages[:need]
                self._pending.popleft()
                slot = free[0]
                mid_batch = bool(self._active.any())
            self._slot_pages[slot] = pages
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:need] = pages
            self._table[slot] = row
            # Longest cached prefix: adopt its pages, prefill the tail.
            cached = self._lookup_prefix(ctx)
            start = len(cached) * self.page_size
            if cached:
                self._adopt_pages(slot, 0, cached)
                self._stats["prefill_tokens_saved"] += start
            nxt, lp = self._local_prefill(slot, req, ctx, start)
            self._finish_admission(slot, req, p, int(nxt), float(lp),
                                   mid_batch)

    def _local_prefix_run(self, ctx: List[int]) -> int:
        """Length (tokens) of the leading full-page run present in the
        LOCAL cache — contains() only, no fetch, no directory RPC."""
        if self._prefix is None:
            return 0
        from ray_tpu.serve import prefix_cache as pc

        keys = pc.prefix_page_keys(
            self._namespace, ctx, self.page_size,
            max_pages=(len(ctx) - 1) // self.page_size)
        n = 0
        for key in keys:
            if not self._prefix.contains(key):
                break
            n += 1
        return n * self.page_size

    def _activate_ready(self):
        """Admit completed remote prefills into free slots: allocate the
        slot + pages now, re-adopt the cached prefix, adopt the streamed
        tail pages, activate.  If the prefix was evicted during the
        round trip (rare), fall back to a full local prefill — the tail
        payload alone can't cover the missing positions."""
        while self._ready:
            req, result, start = self._ready[0]
            ctx = req.context()
            p = len(ctx)
            need = math.ceil(p / self.page_size)
            with self._lock:
                free = [s for s in range(self.max_slots)
                        if not self._active[s]]
                if not free:
                    return
                pages = self.pool.alloc(need + 1)
                if pages is None:
                    return
                self.pool.free(pages[need:])
                pages = pages[:need]
                slot = free[0]
                mid_batch = bool(self._active.any())
                self._ready.popleft()
            self._slot_pages[slot] = pages
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:need] = pages
            self._table[slot] = row
            k_np, v_np, next_tok, meta = result
            first_page = start // self.page_size
            if start:
                cached = self._lookup_prefix(ctx, max_pages=first_page)
                if len(cached) < first_page:
                    self._stats["prefill_prefix_fallback"] += 1
                    hit = len(cached) * self.page_size
                    if cached:
                        self._adopt_pages(slot, 0, cached)
                        self._stats["prefill_tokens_saved"] += hit
                    nxt, lp = self._local_prefill(slot, req, ctx, hit)
                    self._finish_admission(slot, req, p, int(nxt),
                                           float(lp), mid_batch)
                    continue
                self._adopt_pages(slot, 0, cached)
                self._stats["prefill_tokens_saved"] += start
            self._adopt_pages(
                slot, first_page,
                [(k_np[:, j], v_np[:, j]) for j in range(k_np.shape[1])])
            self._stats["wire_bytes"] += int(meta.get("wire_bytes", 0))
            self._stats["wire_fp32_bytes"] += int(meta.get("fp32_bytes", 0))
            if meta.get("exact", True):
                self._publish_prefix(ctx, slot)
            self._finish_admission(slot, req, p, int(next_tok),
                                   float(meta.get("next_logp", float("nan"))),
                                   mid_batch)

    def _local_prefill(self, slot: int, req: _Request, ctx: List[int],
                       start: int):
        """Run the (full or cache-aware tail) prefill into the slot's
        pages; returns (sampled next token, its behavior logprob)."""
        p = len(ctx)
        row = self._table[slot]
        s = req.sampling
        tail_len = p - start
        self._stats["prefill_tokens"] += tail_len
        if start == 0:
            bucket = self._bucket_for(p)
            toks = np.zeros((bucket,), np.int32)
            toks[:p] = ctx
            fn = self._prefill_fn(bucket)
            self._k_pages, self._v_pages, nxt, lp = fn(
                self._params, self._k_pages, self._v_pages, row, toks,
                np.int32(p), np.float32(s.temperature), np.float32(s.top_p),
                np.int32(s.seed))
        else:
            bucket = self._bucket_for(tail_len)
            toks = np.zeros((bucket,), np.int32)
            toks[:tail_len] = ctx[start:]
            fn = self._tail_prefill_fn(bucket)
            self._k_pages, self._v_pages, nxt, lp = fn(
                self._params, self._k_pages, self._v_pages, row, toks,
                np.int32(start), np.int32(p), np.float32(s.temperature),
                np.float32(s.top_p), np.int32(s.seed))
        self._publish_prefix(ctx, slot)
        return nxt, lp

    def _finish_admission(self, slot: int, req: _Request, p: int,
                          next_tok: int, next_logp: float, mid_batch: bool):
        """Shared tail of every admission path: the slot's KV covers
        positions [0, p) and ``next_tok`` is the sampled token at p."""
        if self._spec:
            self._warm_draft(slot, req.context())
        s = req.sampling
        self._stats["admitted"] += 1
        if mid_batch:
            self._stats["admitted_mid_batch"] += 1
        self._observe_queue_wait(time.monotonic() - req.submitted)
        self._lengths[slot] = p
        self._last_tok[slot] = next_tok
        self._temps[slot] = s.temperature
        self._top_ps[slot] = s.top_p
        self._seeds[slot] = s.seed
        req.admit_seq = self._admit_counter
        self._admit_counter += 1
        with self._lock:
            self._active[slot] = True
        self._slot_req[slot] = req
        self._append_token(slot, req, next_tok, next_logp)

    def _warm_draft(self, slot: int, ctx: List[int]):
        """Spec mode: full draft prefill of the context into the draft
        page arrays (same table row as the target)."""
        p = len(ctx)
        bucket = self._bucket_for(p)
        toks = np.zeros((bucket,), np.int32)
        toks[:p] = ctx
        fn = self._draft_prefill_fn(bucket)
        self._dk_pages, self._dv_pages = fn(
            self._draft_params, self._dk_pages, self._dv_pages,
            self._table[slot], toks, np.int32(p))

    # ------------------------------------------------------------------
    # prefix cache: lookup / adopt / publish
    # ------------------------------------------------------------------
    def _lookup_prefix(self, ctx: List[int],
                       max_pages: Optional[int] = None) -> List[tuple]:
        """(k, v) host arrays for the longest cached run of leading full
        pages — local LRU first, then the cluster directory (refs
        fetched with one get_many and written through to the local
        cache).  Capped at (len-1)//page_size so at least one position
        is always freshly computed (the sampled next token needs a
        logits row).  Never raises: a broken directory is a miss."""
        if self._prefix is None:
            return []
        from ray_tpu.serve import prefix_cache as pc

        p = len(ctx)
        cap = (p - 1) // self.page_size
        if max_pages is not None:
            cap = min(cap, max_pages)
        keys = pc.prefix_page_keys(self._namespace, ctx, self.page_size,
                                   max_pages=cap)
        out: List[tuple] = []
        miss_at = len(keys)
        for i, key in enumerate(keys):
            entry = self._prefix.get(key)
            if entry is None:
                miss_at = i
                break
            out.append(entry)
        if out:
            self._stats["prefix_hit_pages"] += len(out)
        if miss_at >= len(keys) or self._directory is None:
            return out
        try:
            import ray_tpu

            rest = keys[miss_at:]
            entries = ray_tpu.get(
                self._directory.lookup_many.remote(rest),
                timeout=self._directory_timeout)
            run = []
            for e in entries:
                if e is None:
                    break
                run.append(e)
            if not run:
                return out
            refs = [r for e in run for r in e]
            vals = ray_tpu.get_many(refs, timeout=self._directory_timeout)
            for j in range(len(run)):
                k_np, v_np = vals[2 * j], vals[2 * j + 1]
                self._prefix.put(rest[j], k_np, v_np)
                out.append((k_np, v_np))
            self._stats["prefix_hit_pages"] += len(run)
            self._stats["prefix_remote_hit_pages"] += len(run)
        except Exception:
            pass  # the cache is an optimization, never a failure source
        return out

    def _adopt_pages(self, slot: int, first_page: int, pages: List[tuple]):
        """Scatter host (k, v) page arrays into the slot's device pages
        starting at page index ``first_page`` (one fixed-shape compiled
        scatter; unused rows route to scratch)."""
        n = len(pages)
        if n == 0:
            return
        ids = np.zeros((self.pages_per_slot,), np.int32)
        ids[:n] = self._table[slot, first_page:first_page + n]
        bk, bv = self._adopt_buf_k, self._adopt_buf_v
        for j, (k_np, v_np) in enumerate(pages):
            bk[:, j] = k_np
            bv[:, j] = v_np
        bk[:, n:] = 0
        bv[:, n:] = 0
        self._k_pages, self._v_pages = self._adopt(
            self._k_pages, self._v_pages, ids, bk, bv)

    def _publish_prefix(self, ctx: List[int], slot: int):
        """Snapshot every full page of ``ctx`` into the local LRU and
        (when a directory is attached) the object plane.  Pages are
        immutable once full — the snapshot is a host copy, later decode
        writes touch later pages."""
        if self._prefix is None:
            return
        from ray_tpu.serve import prefix_cache as pc

        p = len(ctx)
        n_full = p // self.page_size
        if n_full == 0:
            return
        keys = pc.prefix_page_keys(self._namespace, ctx, self.page_size,
                                   max_pages=n_full)
        to_publish = []
        for i, key in enumerate(keys):
            if self._prefix.contains(key):
                continue
            page_id = int(self._table[slot, i])
            k_np = np.asarray(self._k_pages[:, page_id])
            v_np = np.asarray(self._v_pages[:, page_id])
            self._prefix.put(key, k_np, v_np)
            self._stats["prefix_published_pages"] += 1
            to_publish.append((key, k_np, v_np))
        if self._directory is None or not to_publish:
            return
        try:
            import ray_tpu

            arrays = [a for _, k_np, v_np in to_publish
                      for a in (k_np, v_np)]
            refs = ray_tpu.put_many(arrays)
            for j, (key, _, _) in enumerate(to_publish):
                k_ref, v_ref = refs[2 * j], refs[2 * j + 1]
                # Hold our refs across the publish handoff (bounded; the
                # directory is the durable holder once it pins them).
                self._published_refs[key] = (k_ref, v_ref)
                while len(self._published_refs) > 256:
                    self._published_refs.popitem(last=False)
                # Refs nested in a list: a top-level ref arg would
                # be materialized by the task runtime (see
                # PrefixDirectory.publish).
                self._directory.publish.remote(key, [k_ref, v_ref])
        except Exception:
            pass

    # ------------------------------------------------------------------
    # disaggregated prefill: poll + adopt streamed KV pages
    # ------------------------------------------------------------------
    def _poll_prefill(self):
        """Collect completed remote prefills into the ready queue; decode
        for already-active slots never waits on these, and activation
        happens at the next token boundary with a free slot."""
        with self._lock:
            awaiting = list(self._awaiting)
        for entry in awaiting:
            req, job, start = entry
            try:
                result = job.poll()
            except Exception as e:  # noqa: BLE001 — typed per-request fail
                with self._lock:
                    if entry in self._awaiting:
                        self._awaiting.remove(entry)
                req.finish(error=e)
                continue
            if result is None:
                continue
            with self._lock:
                self._awaiting.remove(entry)
                self._ready.append((req, result, start))

    # ------------------------------------------------------------------
    # decode steps
    # ------------------------------------------------------------------
    def _grow(self):
        """Allocate pages for every active slot whose write horizon
        crosses a page boundary; preempt the youngest other request when
        the pool is dry (vLLM-style recompute preemption).  The horizon
        is one token, or ``spec_tokens`` positions in spec mode (the
        verify step scatters the whole window)."""
        horizon = self.spec_tokens if self._spec else 1
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            pos = int(self._lengths[slot])
            page_needed = min(pos + horizon - 1,
                              self.max_ctx - 1) // self.page_size
            while page_needed >= len(self._slot_pages[slot]):
                got = self.pool.alloc(1)
                if got is not None:
                    self._table[slot, len(self._slot_pages[slot])] = got[0]
                    self._slot_pages[slot].append(got[0])
                    continue
                victim = self._pick_victim(exclude=slot)
                if victim is None:
                    req = self._slot_req[slot]
                    self._retire(slot, req, error=KVPoolExhaustedError(
                        f"request {req.id} needs page {page_needed + 1} "
                        f"but the pool ({self.pool.capacity} pages) is "
                        f"exhausted and no other request can be "
                        f"preempted"))
                    break
                self._preempt(victim)

    def _pick_victim(self, exclude: int) -> Optional[int]:
        best, best_seq = None, -1
        for s in range(self.max_slots):
            if s == exclude or not self._active[s]:
                continue
            seq = self._slot_req[s].admit_seq
            if seq > best_seq:
                best, best_seq = s, seq
        return best

    def _preempt(self, slot: int):
        req = self._slot_req.pop(slot)
        self.pool.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self._table[slot] = 0
        self._lengths[slot] = 0
        self._stats["preemptions"] += 1
        with self._lock:
            self._active[slot] = False
            self._pending.appendleft(req)  # readmitted first, from context()

    def _decode_once(self):
        n_active = int(self._active.sum())
        self._k_pages, self._v_pages, nxt, lps = self._decode(
            self._params, self._k_pages, self._v_pages, self._table,
            self._lengths, self._last_tok, self._active, self._temps,
            self._top_ps, self._seeds)
        nxt = np.asarray(nxt)
        lps = np.asarray(lps)
        self._stats["steps"] += 1
        self._stats["tokens"] += n_active
        self._occupancy_sum += n_active / self.max_slots
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            self._lengths[slot] += 1  # the last token's K/V just landed
            req = self._slot_req[slot]
            tok = int(nxt[slot])
            self._last_tok[slot] = tok
            self._append_token(slot, req, tok, float(lps[slot]))

    def _decode_once_spec(self):
        """Draft k-1 proposals per slot, verify the [slots, k] window in
        ONE target step, accept the longest matching prefix plus the
        target's correction token.  Because sampling keys depend only on
        (seed, absolute position), the emitted stream is bitwise the
        non-speculative stream — the draft only sets the tokens/step."""
        k = self.spec_tokens
        n_active = int(self._active.sum())
        proposals = np.zeros((self.max_slots, k - 1), np.int32)
        d_last = self._last_tok.copy()
        for j in range(k - 1):
            self._dk_pages, self._dv_pages, nxt, _dlp = self._draft_decode(
                self._draft_params, self._dk_pages, self._dv_pages,
                self._table, self._lengths + j, d_last, self._active,
                self._temps, self._top_ps, self._seeds)
            d_last = np.asarray(nxt)
            proposals[:, j] = d_last
        # Catch-up step: write the LAST proposal's draft KV (position
        # len+k-1).  On full acceptance that position becomes part of
        # the valid cache next iteration, and without this write the
        # draft would read a stale row and desync; on partial
        # acceptance the row sits beyond kv_lengths and is overwritten
        # before it is ever read.  The sampled output is discarded.
        self._dk_pages, self._dv_pages, _, _ = self._draft_decode(
            self._draft_params, self._dk_pages, self._dv_pages,
            self._table, self._lengths + (k - 1), d_last, self._active,
            self._temps, self._top_ps, self._seeds)
        window = np.concatenate(
            [self._last_tok[:, None], proposals], axis=1)
        self._k_pages, self._v_pages, sampled, v_logps = self._verify(
            self._params, self._k_pages, self._v_pages, self._table,
            self._lengths, window, self._active, self._temps, self._top_ps,
            self._seeds)
        sampled = np.asarray(sampled)  # [slots, k]: tokens at len+1..len+k
        v_logps = np.asarray(v_logps)
        self._stats["steps"] += 1
        self._stats["spec_steps"] += 1
        self._occupancy_sum += n_active / self.max_slots
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            req = self._slot_req[slot]
            m = 0
            while m < k - 1 and proposals[slot, m] == sampled[slot, m]:
                m += 1
            emit = m + 1  # matched proposals + the target's own token
            self._stats["spec_proposed"] += k - 1
            self._stats["spec_accepted"] += m
            req.spec_proposed += k - 1
            req.spec_accepted += m
            self._stats["tokens"] += emit
            self._lengths[slot] += emit
            self._last_tok[slot] = int(sampled[slot, emit - 1])
            for j in range(emit):
                self._append_token(slot, req, int(sampled[slot, j]),
                                   float(v_logps[slot, j]))
                if not self._active[slot]:
                    break  # retired mid-window (EOS / max_new_tokens)

    def _append_token(self, slot: int, req: _Request, tok: int,
                      logp: float = float("nan")):
        req.out.append(tok)
        req.out_logps.append(logp)
        req.out_versions.append(self._weight_version)
        finished = (len(req.out) >= req.max_new_tokens
                    or (req.eos_id is not None and tok == req.eos_id))
        if finished:
            self._retire(slot, req)
        elif len(req.out) - req.streamed >= self.chunk_tokens:
            req.chunks.put(req.out[req.streamed:])
            req.streamed = len(req.out)

    def _retire(self, slot: int, req: _Request,
                error: Optional[BaseException] = None):
        self.pool.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self._table[slot] = 0
        self._lengths[slot] = 0
        self._slot_req.pop(slot, None)
        with self._lock:
            self._active[slot] = False
            self._evict_consumed_locked()
        self._stats["completed"] += 1
        req.finish(error=error)

    def _evict_consumed_locked(self):
        """Bound the registry without losing undrained streams: only
        finished requests whose consumer has the terminal state
        (``consumed``) are dropped — a finished streaming request whose
        chunk queue hasn't been drained survives, so late ``next_chunk``
        pulls never lose tail chunks (regression: ISSUE 13)."""
        if len(self._requests) <= self.REGISTRY_LIMIT:
            return
        for rid in list(self._requests):
            if len(self._requests) <= self.REGISTRY_FLOOR:
                break
            r = self._requests[rid]
            if r.done.is_set() and r.consumed:
                del self._requests[rid]

    # ------------------------------------------------------------------
    # metrics (best-effort: the engine also runs without a ray runtime)
    # ------------------------------------------------------------------
    def _ensure_metrics(self):
        if self._metrics is None:
            from ray_tpu.util import metrics as um

            self._metrics = {
                "tokens": um.Meter("serve_tokens",
                                   "Tokens generated by the decode engine"),
                "requests": um.Meter("serve_requests",
                                     "Requests completed by the engine"),
                "inflight": um.Gauge("serve_inflight_requests",
                                     "Active + queued engine requests"),
                "occupancy": um.Gauge("serve_batch_occupancy",
                                      "Active slots / max_slots"),
                "pages_in_use": um.Gauge("serve_kv_pages_in_use",
                                         "KV cache pages allocated"),
                "pages_free": um.Gauge("serve_kv_pages_free",
                                       "KV cache pages free"),
                "tokens_per_s": um.Gauge("serve_tokens_per_s",
                                         "Engine decode throughput"),
                "prefix_hits": um.Meter(
                    "serve_prefix_hit_pages",
                    "KV pages adopted from the prefix cache"),
                "spec_accept": um.Gauge(
                    "serve_spec_acceptance",
                    "Speculative-decode acceptance rate (accepted / "
                    "proposed draft tokens)"),
                "queue_wait": um.Histogram(
                    "serve_queue_wait_s", "Submit-to-admission wait",
                    boundaries=(0.001, 0.01, 0.1, 1.0, 10.0)),
            }

    def _observe_queue_wait(self, wait_s: float):
        try:
            self._ensure_metrics()
            self._metrics["queue_wait"].observe(wait_s)
        except Exception:
            pass

    def _flush_metrics(self):
        now = time.monotonic()
        if now - self._metrics_flush < 2.0:
            return
        self._metrics_flush = now
        try:
            self._ensure_metrics()
            m, st = self._metrics, self._stats
            m["tokens"].mark(st["tokens"] - m["tokens"].total())
            m["requests"].mark(st["completed"] - m["requests"].total())
            m["prefix_hits"].mark(
                st["prefix_hit_pages"] - m["prefix_hits"].total())
            if st.get("spec_proposed", 0):
                m["spec_accept"].set(
                    st["spec_accepted"] / st["spec_proposed"])
            with self._lock:
                inflight = int(self._active.sum()) + len(self._pending)
                occ = float(self._active.sum()) / self.max_slots
            m["inflight"].set(inflight)
            m["occupancy"].set(occ)
            pool = self.pool.stats()
            m["pages_in_use"].set(pool["in_use"])
            m["pages_free"].set(pool["free"])
            m["tokens_per_s"].set(st["tokens"] / max(1e-9,
                                                     now - self._t0))
            for meter in (m["tokens"], m["requests"], m["prefix_hits"]):
                meter.flush()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# The naive per-request baseline and shared model builders
# ---------------------------------------------------------------------------
class NaiveLM:
    """Per-request serving baseline: batch-1, no KV cache — every token
    re-runs the full-context forward pass at a fixed padded width (one
    compile; padding is exact under the causal mask).  This is the
    reference the engine must be token-identical to, and the denominator
    of the continuous-batching speedup in bench.py.  ``sampling`` makes
    it the seeded-sampling reference too: it draws with the same
    ``fold_in(PRNGKey(seed), position)`` keys over full-context logits,
    so engine sampling must reproduce it bitwise."""

    def __init__(self, model, params, width: int):
        import jax
        import jax.numpy as jnp

        from ray_tpu.serve.sampling import sample_tokens

        self.params = params
        self.width = width

        def step(params, ids, n, temp, top_p, seed):
            logits = model.apply({"params": params}, ids)
            return sample_tokens(
                logits[0, n - 1][None], jnp.reshape(n, (1,)),
                jnp.reshape(temp, (1,)), jnp.reshape(top_p, (1,)),
                jnp.reshape(seed, (1,)))[0]

        self._step = jax.jit(step)

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 sampling: Optional[SamplingParams] = None) -> List[int]:
        s = sampling or GREEDY
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        buf = np.zeros((1, self.width), np.int32)
        buf[0, :len(prompt)] = prompt
        n = len(prompt)
        out: List[int] = []
        for _ in range(max_new_tokens):
            tok = int(self._step(self.params, buf, np.int32(n),
                                 np.float32(s.temperature),
                                 np.float32(s.top_p), np.int32(s.seed)))
            out.append(tok)
            if n < self.width:
                buf[0, n] = tok
            n += 1
            if eos_id is not None and tok == eos_id:
                break
        return out


def build_model(model_kind: str, config_kw: Optional[dict] = None,
                seed: int = 0):
    """(model, params) for a serving replica.  Seeded init: every replica
    of a deployment materializes identical weights without shipping
    params through init args."""
    import jax
    import jax.numpy as jnp

    config_kw = dict(config_kw or {})
    if model_kind == "gpt2":
        from ray_tpu.models import GPT2, GPT2Config

        model = GPT2(GPT2Config.tiny(**config_kw) if config_kw.pop(
            "tiny", True) else GPT2Config(**config_kw))
    elif model_kind == "llama":
        from ray_tpu.models import Llama, LlamaConfig

        model = Llama(LlamaConfig.tiny(**config_kw) if config_kw.pop(
            "tiny", True) else LlamaConfig(**config_kw))
    else:
        raise ValueError(f"unknown model_kind {model_kind!r}")
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), ids)["params"]
    return model, params


def cache_namespace_for(model_kind: str, config_kw: Optional[dict],
                        seed: int, page_size: int,
                        weight_version: Optional[int] = None) -> str:
    """Stable prefix-cache namespace: everything that changes a page's
    bytes (model family, config, init seed, page geometry — and, since
    hot weight swaps exist, the weight version) must be in the address,
    so deployments sharing an object plane can't poison each other.

    ``weight_version=None`` returns the UNVERSIONED base — the form to
    hand ``LLMEngine(cache_namespace=...)``, which folds its own live
    version in on every ``swap_weights`` (see
    ``prefix_cache.versioned_namespace``).  Pass an explicit version to
    address a specific weight generation from outside the engine
    (tests, external publishers)."""
    kw = sorted((config_kw or {}).items())
    base = f"{model_kind}|{kw!r}|seed{seed}|ps{page_size}"
    if weight_version is None:
        return base
    from ray_tpu.serve.prefix_cache import versioned_namespace

    return versioned_namespace(base, weight_version)


class LLMServer:
    """Serve deployment callable hosting one LLMEngine per replica.

    Use with ``@serve.deployment`` / ``serve.run``; autoscaling sees the
    handle's in-flight count like any deployment, so a saturating client
    scales replicas up through the normal controller loop.  Three entry
    points:

    - ``__call__({"tokens": [...], "max_new_tokens": n, "temperature":
      t, "top_p": p, "seed": s})`` — JSON/HTTP.
    - ``generate_batch(refs, ...)`` — the zero-copy object-plane path
      (prompt refs in via ``get_many``, output refs back via
      ``put_many``); pair with :func:`generate_many` client-side.
    - ``submit_stream``/``next_chunk`` — pull-based token streaming.

    Serving-tier knobs: ``draft_config_kw`` + ``spec_tokens`` enable
    speculative decoding (the draft is built from the same seed, so
    replicas agree); ``prefix_cache=True`` turns on the local prefix
    cache, ``prefix_directory=`` (a ``prefix_cache.create_directory()``
    handle) shares it cluster-wide; ``prefill=`` (a PrefillWorker
    deployment handle) disaggregates prefill.
    """

    def __init__(self, model_kind: str = "gpt2",
                 config_kw: Optional[dict] = None, seed: int = 0,
                 draft_config_kw: Optional[dict] = None,
                 spec_tokens=_DEF, prefix_cache=None,
                 prefix_directory=None, prefill=None,
                 **engine_kw):
        model, params = build_model(model_kind, config_kw, seed)
        draft_model = draft_params = None
        if draft_config_kw is not None:
            draft_model, draft_params = build_model(
                model_kind, draft_config_kw, seed)
        page_size = int(_cfg("serve_page_size",
                             engine_kw.get("page_size", _DEF), 16))
        self.engine = LLMEngine(
            model, params, draft_model=draft_model,
            draft_params=draft_params, spec_tokens=spec_tokens,
            prefix_cache=prefix_cache, prefix_directory=prefix_directory,
            prefill=prefill,
            cache_namespace=cache_namespace_for(model_kind, config_kw,
                                                seed, page_size),
            **engine_kw)

    @staticmethod
    def _sampling_of(request: dict) -> SamplingParams:
        return SamplingParams(
            temperature=float(request.get("temperature", 0.0)),
            top_p=float(request.get("top_p", 1.0)),
            seed=int(request.get("seed", 0)))

    def __call__(self, request: dict) -> dict:
        rid = self.engine.submit(request["tokens"],
                                 int(request.get("max_new_tokens", 16)),
                                 request.get("eos_id"),
                                 sampling=self._sampling_of(request))
        return {"tokens": self.engine.result(rid, timeout=120.0)}

    def generate_batch(self, prompts, max_new_tokens: int = 16,
                       eos_id: Optional[int] = None, as_refs: bool = True,
                       sampling: Optional[list] = None):
        import ray_tpu

        if prompts and isinstance(prompts[0], ray_tpu.ObjectRef):
            prompts = ray_tpu.get_many(list(prompts))
        if sampling is None:
            sampling = [None] * len(prompts)
        rids = [self.engine.submit(p, max_new_tokens, eos_id, sampling=s)
                for p, s in zip(prompts, sampling)]
        outs = [self.engine.result(r, timeout=120.0) for r in rids]
        if not as_refs:
            return outs
        return ray_tpu.put_many([np.asarray(o, np.int32) for o in outs])

    def submit_stream(self, prompt, max_new_tokens: int = 16,
                      eos_id: Optional[int] = None,
                      sampling: Optional[SamplingParams] = None) -> int:
        import ray_tpu

        if isinstance(prompt, ray_tpu.ObjectRef):
            prompt = ray_tpu.get(prompt)
        return self.engine.submit(prompt, max_new_tokens, eos_id,
                                  sampling=sampling)

    def next_chunk(self, rid: int, timeout: float = 60.0):
        """Next streamed token chunk, or None when the request retired."""
        req = self.engine._requests[rid]
        try:
            chunk = req.chunks.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"no chunk for request {rid} in {timeout}s")
        if chunk is None:
            req.consumed = True
        return chunk

    def swap_weights(self, params, version: int,
                     timeout: Optional[float] = 60.0) -> int:
        """Hot-swap this replica's engine weights (``params`` may be the
        broadcast ObjectRef — one learner ``put`` serves every replica;
        replicas resolving the same version concurrently stripe the pull
        across holders and serve each other's landed ranges, see
        docs/PERFORMANCE.md "Multi-source transfers")."""
        return self.engine.swap_weights(params, version, timeout=timeout)

    def generate_rollouts(self, prompts, max_new_tokens: int = 16,
                          eos_id: Optional[int] = None,
                          sampling: Optional[list] = None):
        """Version-stamped rollouts (tokens + behavior logprobs) for the
        RLHF loop; accepts prompt refs like ``generate_batch``."""
        import ray_tpu

        if prompts and isinstance(prompts[0], ray_tpu.ObjectRef):
            prompts = ray_tpu.get_many(list(prompts))
        return self.engine.generate_rollouts(
            prompts, max_new_tokens, eos_id, sampling=sampling)

    def stats(self) -> dict:
        return self.engine.stats()

    def request_stats(self, rid: int) -> dict:
        return self.engine.request_stats(rid)

    def autoscale_metric(self) -> float:
        """Engine-load signal for the controller's ``metric_method``
        autoscaling mode: in-flight work per decode slot (1.0 = the
        replica's compiled batch is exactly full)."""
        st = self.engine.stats()
        return (st["active"] + st["pending"]
                + st["prefill_inflight"]) / self.engine.max_slots

    def drain(self):
        """Teardown hook: close the engine (fails in-flight requests with
        a typed error) and any replica-local batchers."""
        self.engine.close()
        from ray_tpu.serve import batching

        batching.close_instance_batchers(self)
        return True


def generate_many(handle, prompts, max_new_tokens: int = 16,
                  eos_id: Optional[int] = None,
                  sampling: Optional[List[SamplingParams]] = None,
                  timeout: float = 120.0) -> List[List[int]]:
    """Client half of the zero-copy request path: one ``put_many`` for
    the prompt batch (one coalesced control-plane notify), one actor call
    carrying refs per affinity group, one ``get_many`` gather of the
    responses.  Prompts are grouped by their prefix affinity key so
    shared-prefix requests land on the replica already holding the
    cached KV pages (see serve/prefix_cache.py)."""
    import ray_tpu
    from ray_tpu.serve.prefix_cache import affinity_key
    from ray_tpu.util import tracing

    # Driver API boundary: the whole request batch (put_many, actor
    # calls, get_many gather, replica decode steps) rides one trace,
    # rooted at this span.
    with tracing.span("serve.generate_many", requests=len(prompts)):
        groups: Dict[str, List[int]] = {}
        for i, p in enumerate(prompts):
            groups.setdefault(affinity_key(p), []).append(i)
        out: List[Optional[List[int]]] = [None] * len(prompts)
        calls = []
        for key, idxs in groups.items():
            refs = ray_tpu.put_many(
                [np.asarray(prompts[i], np.int32) for i in idxs])
            samp = [sampling[i] for i in idxs] if sampling else None
            calls.append((idxs, handle.method("generate_batch").remote(
                refs, max_new_tokens, eos_id, True, samp, _affinity=key)))
        for idxs, call in calls:
            out_refs = ray_tpu.get(call, timeout=timeout)
            vals = ray_tpu.get_many(out_refs)
            for i, v in zip(idxs, vals):
                out[i] = [int(t) for t in v]
        return out
