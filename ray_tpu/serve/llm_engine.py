"""Continuous-batching LLM decode engine with a paged KV cache.

The inference half of the north star: `ray_tpu/serve/` routed and
wall-clock-batched requests, but had no decode path — this module is the
replica-resident engine that turns the models we train
(`ray_tpu/models/gpt2.py`, `llama.py`) into a serving workload
(reference composition: Ray's latency-oriented serving tier over the
task/actor/object substrate, arxiv 1712.05889; engine design follows the
continuous-batching literature — Orca's iteration-level scheduling and
vLLM's paged KV cache).

Three load-bearing ideas:

1. **Fixed-slot compiled decode step.**  The decode program is compiled
   ONCE for `[max_slots]`-shaped inputs (token ids, lengths, page table,
   active mask).  Admitting or retiring a request flips host-side state —
   it never changes a traced shape, so the steady-state loop never
   recompiles.  Prefill compiles per power-of-two prompt bucket (bounded:
   log2(max_ctx) programs).

2. **Token-boundary admission.**  The engine loop runs one decode step
   for ALL in-flight requests, then admits pending requests into free
   slots *between* steps (one prefill each) — a new request joins the
   running batch at the next token boundary instead of waiting for the
   batch to drain (Orca's iteration-level scheduling).

3. **Paged KV cache.**  K/V live in fixed-size pages allocated from a
   device-resident pool (`PagePool` — the SegmentPool free-list recycle
   design from `_private/object_store.py:163`, collapsed to one size
   class because pages are uniform).  A sequence owns `ceil(len/page)`
   pages found through a per-slot page table; the decode step gathers
   pages into the attention view and scatters the new token's K/V back.
   Long and short sequences share the pool without fragmentation, pages
   recycle at retirement, and when the pool runs dry the engine preempts
   the youngest request (its pages free; it restarts later from
   prompt+generated-so-far — greedy decode is deterministic, so resumed
   output is identical and already-streamed chunks are never re-sent).

Request/response payloads ride the object plane zero-copy: see
``generate_many`` (client: ``put_many`` prompts → replica:
``get_many`` → decode → ``put_many`` outputs → client: ``get_many``).
"""
from __future__ import annotations

import collections
import dataclasses
import math
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.exceptions import EngineClosedError, KVPoolExhaustedError

_DEF = object()  # sentinel: constructor arg not given, consult CONFIG


def _cfg(name, given, fallback):
    if given is not _DEF and given is not None:
        return given
    try:
        from ray_tpu._private.config import CONFIG

        v = CONFIG.get(name)
        return v if v else fallback
    except Exception:
        return fallback


class PagePool:
    """Free-list allocator of fixed-size KV-cache pages.

    The SegmentPool design (`_private/object_store.py:163`) applied to
    device memory: pages are created once (the device arrays are
    allocated up front) and recycled through a free list instead of
    re-allocated, so steady-state admission costs a list pop.  Pages are
    uniform, so SegmentPool's power-of-two size classes collapse to one
    free list; the accounting (hits/misses, peak, in-use) keeps the same
    shape so the dashboard reads both pools alike.  Page 0 is the
    scratch page: masked-out lanes of the compiled scatter (inactive
    slots, prompt padding) are routed there so they can never corrupt a
    live sequence."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("PagePool needs >= 2 pages (page 0 is scratch)")
        self.capacity = num_pages - 1  # page 0 reserved
        self._free: collections.deque = collections.deque(range(1, num_pages))
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.peak_in_use = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop n pages, all-or-nothing (a partial grant would deadlock the
        grower against its own reservation)."""
        with self._lock:
            if len(self._free) < n:
                self.misses += 1
                return None
            self.hits += 1
            out = [self._free.popleft() for _ in range(n)]
            self.peak_in_use = max(self.peak_in_use, self.in_use)
            return out

    def free(self, pages: Sequence[int]):
        with self._lock:
            self._free.extend(pages)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"capacity": self.capacity, "free": len(self._free),
                    "in_use": self.in_use, "peak_in_use": self.peak_in_use,
                    "hits": self.hits, "misses": self.misses}


@dataclasses.dataclass
class _Request:
    id: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int]
    submitted: float = dataclasses.field(default_factory=time.monotonic)
    out: List[int] = dataclasses.field(default_factory=list)
    chunks: "queue.Queue" = dataclasses.field(default_factory=queue.Queue)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    error: Optional[BaseException] = None
    streamed: int = 0  # tokens already pushed to the chunk stream
    admit_seq: int = -1  # preemption picks the youngest (highest seq)

    def context(self) -> List[int]:
        """Prompt plus generated-so-far — what a (re)admission prefills.
        Greedy decode is deterministic, so a preempted request resumed
        from this context produces exactly the tokens it would have."""
        return self.prompt + self.out

    def finish(self, error: Optional[BaseException] = None):
        self.error = error
        if self.streamed < len(self.out):
            self.chunks.put(self.out[self.streamed:])
            self.streamed = len(self.out)
        self.chunks.put(None)
        self.done.set()


class LLMEngine:
    """Replica-resident continuous-batching decode engine.

    ``submit()`` is thread-safe and returns immediately; a background
    loop thread owns all device state and serializes prefill/decode.
    ``result()`` blocks for the full output, ``stream()`` yields token
    chunks as they are produced (chunks arrive while the request is
    still decoding).  Greedy (argmax) decoding only — the token-identity
    contract with the uncached reference is what the correctness gates
    assert."""

    def __init__(self, model, params, *, max_slots=_DEF, page_size=_DEF,
                 num_pages: Optional[int] = None,
                 max_ctx: Optional[int] = None,
                 chunk_tokens: int = 8, start: bool = True):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self._model = model
        self._params = params
        c = model.config
        self.num_layers = c.num_layers
        self.head_dim = c.head_dim
        self.kv_heads = getattr(c, "num_kv_heads", c.num_heads)
        self.dtype = c.dtype
        self.max_slots = int(_cfg("serve_max_slots", max_slots, 8))
        self.page_size = int(_cfg("serve_page_size", page_size, 16))
        self.max_ctx = int(max_ctx or c.max_position_embeddings)
        self.pages_per_slot = math.ceil(self.max_ctx / self.page_size)
        self.max_ctx = self.pages_per_slot * self.page_size
        if self.max_ctx > c.max_position_embeddings:
            raise ValueError(
                f"max_ctx {self.max_ctx} (page-rounded) exceeds the model's "
                f"max_position_embeddings {c.max_position_embeddings}")
        # Default pool: full provisioning (+1 scratch) — every slot can
        # reach max_ctx, preemption never fires.  Size it down to share
        # the pool across more slots than worst-case memory allows.
        if num_pages is None:
            num_pages = self.max_slots * self.pages_per_slot + 1
        self.pool = PagePool(num_pages)
        self.chunk_tokens = chunk_tokens

        shape = (self.num_layers, num_pages, self.page_size,
                 self.kv_heads, self.head_dim)
        self._k_pages = jnp.zeros(shape, self.dtype)
        self._v_pages = jnp.zeros(shape, self.dtype)

        # Host-side slot state (the loop thread is the only writer).
        self._table = np.zeros((self.max_slots, self.pages_per_slot),
                               np.int32)
        self._lengths = np.zeros((self.max_slots,), np.int32)
        self._active = np.zeros((self.max_slots,), bool)
        self._last_tok = np.zeros((self.max_slots,), np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(self.max_slots)]
        self._slot_req: Dict[int, _Request] = {}

        self._decode = jax.jit(self._decode_impl, donate_argnums=(1, 2))
        self._prefills: Dict[int, Any] = {}

        self._pending: collections.deque = collections.deque()
        self._requests: Dict[int, _Request] = {}
        self._next_id = 0
        self._admit_counter = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._stats = collections.Counter()
        self._occupancy_sum = 0.0
        self._t0 = time.monotonic()
        self._metrics = None
        self._metrics_flush = 0.0
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="rtpu-llm-engine", daemon=True)
            self._thread.start()

    # ------------------------------------------------------------------
    # public API (any thread)
    # ------------------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               eos_id: Optional[int] = None) -> int:
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.max_ctx:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_ctx {self.max_ctx}")
        with self._cond:
            if self._closed:
                raise EngineClosedError("engine is closed")
            rid = self._next_id
            self._next_id += 1
            req = _Request(rid, prompt, max_new_tokens, eos_id)
            self._requests[rid] = req
            self._pending.append(req)
            self._cond.notify_all()
        return rid

    def result(self, rid: int, timeout: Optional[float] = None) -> List[int]:
        req = self._requests[rid]
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {rid} not done within {timeout}s")
        if req.error is not None:
            raise req.error
        return list(req.out)

    def stream(self, rid: int, timeout: float = 120.0):
        """Yield token chunks (lists) as they are produced; returns when
        the request retires.  Raises the request's error, if any."""
        req = self._requests[rid]
        while True:
            chunk = req.chunks.get(timeout=timeout)
            if chunk is None:
                break
            yield chunk
        if req.error is not None:
            raise req.error

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n_active = int(self._active.sum())
            s = dict(self._stats)
        pool = self.pool.stats()
        steps = s.get("steps", 0)
        out = {
            "active": n_active,
            "pending": len(self._pending),
            "admitted": s.get("admitted", 0),
            "admitted_mid_batch": s.get("admitted_mid_batch", 0),
            "completed": s.get("completed", 0),
            "preemptions": s.get("preemptions", 0),
            "steps": steps,
            "tokens_generated": s.get("tokens", 0),
            "avg_batch_occupancy": (self._occupancy_sum / steps
                                    if steps else 0.0),
            "pages_in_use": pool["in_use"],
            "pages_free": pool["free"],
            "page_pool": pool,
            "prefill_buckets": len(self._prefills),
        }
        cache_size = getattr(self._decode, "_cache_size", None)
        if callable(cache_size):
            out["decode_cache_size"] = cache_size()
        return out

    def close(self, timeout: float = 10.0):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        err = EngineClosedError("engine closed with requests in flight")
        for req in list(self._requests.values()):
            if not req.done.is_set():
                req.finish(error=err)

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _gather_cache(self, pages, table):
        """[L, P, ps, Hkv, D] pages + [slots, pp] table → per-slot
        contiguous [L, slots, max_ctx, Hkv, D] attention view (rows past
        each slot's length are garbage — masked by cached_attention)."""
        g = pages[:, table]  # [L, slots, pp, ps, Hkv, D]
        return g.reshape(self.num_layers, table.shape[0], self.max_ctx,
                         self.kv_heads, self.head_dim)

    def _decode_impl(self, params, k_pages, v_pages, table, lengths,
                     tokens, active):
        """One token for every slot (fixed shapes — compiled once).
        Inactive lanes compute garbage routed to the scratch page."""
        jnp = self._jnp
        L = self.num_layers
        k_cache = self._gather_cache(k_pages, table)
        v_cache = self._gather_cache(v_pages, table)
        kv = [(k_cache[i], v_cache[i]) for i in range(L)]
        logits, new_kvs = self._model.apply(
            {"params": params}, tokens[:, None], lengths[:, None], kv,
            lengths)
        next_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        newk = jnp.stack([nk[0][:, 0] for nk in new_kvs])  # [L,slots,Hkv,D]
        newv = jnp.stack([nk[1][:, 0] for nk in new_kvs])
        slot_ix = jnp.arange(table.shape[0])
        page_col = jnp.minimum(lengths // self.page_size,
                               self.pages_per_slot - 1)
        page_idx = jnp.where(active, table[slot_ix, page_col], 0)
        off = lengths % self.page_size
        k_pages = k_pages.at[:, page_idx, off].set(newk.astype(self.dtype))
        v_pages = v_pages.at[:, page_idx, off].set(newv.astype(self.dtype))
        return k_pages, v_pages, next_tok

    def _prefill_fn(self, bucket: int):
        fn = self._prefills.get(bucket)
        if fn is not None:
            return fn
        jax, jnp = self._jax, self._jnp
        L, ps = self.num_layers, self.page_size

        def prefill(params, k_pages, v_pages, row, tokens, p):
            """tokens: [bucket] ids padded past p; row: [pp] page table
            row.  Returns updated pages + the greedy next token."""
            ids = tokens[None]
            positions = jnp.arange(bucket)[None]
            empty = [(jnp.zeros((1, 0, self.kv_heads, self.head_dim),
                                self.dtype),) * 2 for _ in range(L)]
            logits, new_kvs = self._model.apply(
                {"params": params}, ids, positions, empty,
                jnp.zeros((1,), jnp.int32))
            next_tok = jnp.argmax(logits[0, p - 1]).astype(jnp.int32)
            t = jnp.arange(bucket)
            page_idx = jnp.where(t < p, row[t // ps], 0)
            off = t % ps
            newk = jnp.stack([nk[0][0] for nk in new_kvs])  # [L,bkt,Hkv,D]
            newv = jnp.stack([nk[1][0] for nk in new_kvs])
            k_pages = k_pages.at[:, page_idx, off].set(
                newk.astype(self.dtype))
            v_pages = v_pages.at[:, page_idx, off].set(
                newv.astype(self.dtype))
            return k_pages, v_pages, next_tok

        fn = jax.jit(prefill, donate_argnums=(1, 2))
        self._prefills[bucket] = fn
        return fn

    def _bucket_for(self, p: int) -> int:
        b = 8
        while b < p:
            b <<= 1
        return min(b, self.max_ctx)

    # ------------------------------------------------------------------
    # engine loop (single thread owns the device state)
    # ------------------------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                while (not self._closed and not self._pending
                       and not self._active.any()):
                    self._cond.wait(0.2)
                if self._closed:
                    return
            try:
                self._admit()
                self._grow()
                if self._active.any():
                    self._decode_once()
            except BaseException as e:  # noqa: BLE001 — fail loudly per req
                self._fail_all(e)
                return
            self._flush_metrics()

    def _fail_all(self, e: BaseException):
        with self._lock:
            self._closed = True  # a dead loop must reject new submits
        for req in list(self._requests.values()):
            if not req.done.is_set():
                req.finish(error=e)
        for s in range(self.max_slots):
            if self._slot_pages[s]:
                self.pool.free(self._slot_pages[s])
                self._slot_pages[s] = []
        self._active[:] = False

    def _admit(self):
        """Token-boundary admission: fill free slots from the pending
        queue, one prefill each.  Requires prompt pages + 1 free so the
        first decode token can't immediately force a preemption."""
        while True:
            with self._lock:
                if not self._pending:
                    return
                free = [s for s in range(self.max_slots)
                        if not self._active[s]]
                if not free:
                    return
                req = self._pending[0]
                ctx = req.context()
                need = math.ceil(len(ctx) / self.page_size)
                if need + 1 > self.pool.capacity:
                    # Can never fit, even with the whole pool to itself —
                    # waiting would busy-spin forever.
                    self._pending.popleft()
                    req.finish(error=KVPoolExhaustedError(
                        f"request {req.id} needs {need + 1} pages but the "
                        f"pool holds {self.pool.capacity}"))
                    continue
                pages = self.pool.alloc(need + 1)
                if pages is None:
                    return  # pool too tight right now; retry next boundary
                self.pool.free(pages[need:])  # only reserve the +1 headroom
                pages = pages[:need]
                self._pending.popleft()
                slot = free[0]
                mid_batch = bool(self._active.any())
            self._stats["admitted"] += 1
            if mid_batch:
                self._stats["admitted_mid_batch"] += 1
            self._observe_queue_wait(time.monotonic() - req.submitted)
            self._slot_pages[slot] = pages
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:need] = pages
            self._table[slot] = row
            p = len(ctx)
            bucket = self._bucket_for(p)
            toks = np.zeros((bucket,), np.int32)
            toks[:p] = ctx
            fn = self._prefill_fn(bucket)
            self._k_pages, self._v_pages, nxt = fn(
                self._params, self._k_pages, self._v_pages, row, toks,
                np.int32(p))
            tok = int(nxt)
            self._lengths[slot] = p
            self._last_tok[slot] = tok
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            with self._lock:
                self._active[slot] = True
            self._slot_req[slot] = req
            self._append_token(slot, req, tok)

    def _grow(self):
        """Allocate the next page for every active slot whose write head
        crossed a page boundary; preempt the youngest other request when
        the pool is dry (vLLM-style recompute preemption)."""
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            pos = int(self._lengths[slot])
            page_needed = pos // self.page_size
            while page_needed >= len(self._slot_pages[slot]):
                got = self.pool.alloc(1)
                if got is not None:
                    self._table[slot, len(self._slot_pages[slot])] = got[0]
                    self._slot_pages[slot].append(got[0])
                    continue
                victim = self._pick_victim(exclude=slot)
                if victim is None:
                    req = self._slot_req[slot]
                    self._retire(slot, req, error=KVPoolExhaustedError(
                        f"request {req.id} needs page {page_needed + 1} "
                        f"but the pool ({self.pool.capacity} pages) is "
                        f"exhausted and no other request can be "
                        f"preempted"))
                    break
                self._preempt(victim)

    def _pick_victim(self, exclude: int) -> Optional[int]:
        best, best_seq = None, -1
        for s in range(self.max_slots):
            if s == exclude or not self._active[s]:
                continue
            seq = self._slot_req[s].admit_seq
            if seq > best_seq:
                best, best_seq = s, seq
        return best

    def _preempt(self, slot: int):
        req = self._slot_req.pop(slot)
        self.pool.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self._table[slot] = 0
        self._lengths[slot] = 0
        self._stats["preemptions"] += 1
        with self._lock:
            self._active[slot] = False
            self._pending.appendleft(req)  # readmitted first, from context()

    def _decode_once(self):
        n_active = int(self._active.sum())
        self._k_pages, self._v_pages, nxt = self._decode(
            self._params, self._k_pages, self._v_pages, self._table,
            self._lengths, self._last_tok, self._active)
        nxt = np.asarray(nxt)
        self._stats["steps"] += 1
        self._stats["tokens"] += n_active
        self._occupancy_sum += n_active / self.max_slots
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            self._lengths[slot] += 1  # the last token's K/V just landed
            req = self._slot_req[slot]
            tok = int(nxt[slot])
            self._last_tok[slot] = tok
            self._append_token(slot, req, tok)

    def _append_token(self, slot: int, req: _Request, tok: int):
        req.out.append(tok)
        finished = (len(req.out) >= req.max_new_tokens
                    or (req.eos_id is not None and tok == req.eos_id))
        if finished:
            self._retire(slot, req)
        elif len(req.out) - req.streamed >= self.chunk_tokens:
            req.chunks.put(req.out[req.streamed:])
            req.streamed = len(req.out)

    def _retire(self, slot: int, req: _Request,
                error: Optional[BaseException] = None):
        self.pool.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self._table[slot] = 0
        self._lengths[slot] = 0
        self._slot_req.pop(slot, None)
        with self._lock:
            self._active[slot] = False
            # Bound the registry: drop the oldest finished requests once
            # past 4096 entries (a long-lived replica must not leak one
            # _Request per call).
            if len(self._requests) > 4096:
                for rid in list(self._requests):
                    if len(self._requests) <= 2048:
                        break
                    if self._requests[rid].done.is_set():
                        del self._requests[rid]
        self._stats["completed"] += 1
        req.finish(error=error)

    # ------------------------------------------------------------------
    # metrics (best-effort: the engine also runs without a ray runtime)
    # ------------------------------------------------------------------
    def _ensure_metrics(self):
        if self._metrics is None:
            from ray_tpu.util import metrics as um

            self._metrics = {
                "tokens": um.Meter("serve_tokens",
                                   "Tokens generated by the decode engine"),
                "requests": um.Meter("serve_requests",
                                     "Requests completed by the engine"),
                "inflight": um.Gauge("serve_inflight_requests",
                                     "Active + queued engine requests"),
                "occupancy": um.Gauge("serve_batch_occupancy",
                                      "Active slots / max_slots"),
                "pages_in_use": um.Gauge("serve_kv_pages_in_use",
                                         "KV cache pages allocated"),
                "pages_free": um.Gauge("serve_kv_pages_free",
                                       "KV cache pages free"),
                "tokens_per_s": um.Gauge("serve_tokens_per_s",
                                         "Engine decode throughput"),
                "queue_wait": um.Histogram(
                    "serve_queue_wait_s", "Submit-to-admission wait",
                    boundaries=(0.001, 0.01, 0.1, 1.0, 10.0)),
            }

    def _observe_queue_wait(self, wait_s: float):
        try:
            self._ensure_metrics()
            self._metrics["queue_wait"].observe(wait_s)
        except Exception:
            pass

    def _flush_metrics(self):
        now = time.monotonic()
        if now - self._metrics_flush < 2.0:
            return
        self._metrics_flush = now
        try:
            self._ensure_metrics()
            m, st = self._metrics, self._stats
            m["tokens"].mark(st["tokens"] - m["tokens"].total())
            m["requests"].mark(st["completed"] - m["requests"].total())
            with self._lock:
                inflight = int(self._active.sum()) + len(self._pending)
                occ = float(self._active.sum()) / self.max_slots
            m["inflight"].set(inflight)
            m["occupancy"].set(occ)
            pool = self.pool.stats()
            m["pages_in_use"].set(pool["in_use"])
            m["pages_free"].set(pool["free"])
            m["tokens_per_s"].set(st["tokens"] / max(1e-9,
                                                     now - self._t0))
            for meter in (m["tokens"], m["requests"]):
                meter.flush()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# The naive per-request baseline and shared model builders
# ---------------------------------------------------------------------------
class NaiveLM:
    """Per-request serving baseline: batch-1, no KV cache — every token
    re-runs the full-context forward pass at a fixed padded width (one
    compile; padding is exact under the causal mask).  This is the
    reference the engine must be token-identical to, and the denominator
    of the continuous-batching speedup in bench.py."""

    def __init__(self, model, params, width: int):
        import jax
        import jax.numpy as jnp

        self.params = params
        self.width = width

        def step(params, ids, n):
            logits = model.apply({"params": params}, ids)
            return jnp.argmax(logits[0, n - 1]).astype(jnp.int32)

        self._step = jax.jit(step)

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 eos_id: Optional[int] = None) -> List[int]:
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        buf = np.zeros((1, self.width), np.int32)
        buf[0, :len(prompt)] = prompt
        n = len(prompt)
        out: List[int] = []
        for _ in range(max_new_tokens):
            tok = int(self._step(self.params, buf, np.int32(n)))
            out.append(tok)
            if n < self.width:
                buf[0, n] = tok
            n += 1
            if eos_id is not None and tok == eos_id:
                break
        return out


def build_model(model_kind: str, config_kw: Optional[dict] = None,
                seed: int = 0):
    """(model, params) for a serving replica.  Seeded init: every replica
    of a deployment materializes identical weights without shipping
    params through init args."""
    import jax
    import jax.numpy as jnp

    config_kw = dict(config_kw or {})
    if model_kind == "gpt2":
        from ray_tpu.models import GPT2, GPT2Config

        model = GPT2(GPT2Config.tiny(**config_kw) if config_kw.pop(
            "tiny", True) else GPT2Config(**config_kw))
    elif model_kind == "llama":
        from ray_tpu.models import Llama, LlamaConfig

        model = Llama(LlamaConfig.tiny(**config_kw) if config_kw.pop(
            "tiny", True) else LlamaConfig(**config_kw))
    else:
        raise ValueError(f"unknown model_kind {model_kind!r}")
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), ids)["params"]
    return model, params


class LLMServer:
    """Serve deployment callable hosting one LLMEngine per replica.

    Use with ``@serve.deployment`` / ``serve.run``; autoscaling sees the
    handle's in-flight count like any deployment, so a saturating client
    scales replicas up through the normal controller loop.  Three entry
    points:

    - ``__call__({"tokens": [...], "max_new_tokens": n})`` — JSON/HTTP.
    - ``generate_batch(refs, ...)`` — the zero-copy object-plane path
      (prompt refs in via ``get_many``, output refs back via
      ``put_many``); pair with :func:`generate_many` client-side.
    - ``submit_stream``/``next_chunk`` — pull-based token streaming.
    """

    def __init__(self, model_kind: str = "gpt2",
                 config_kw: Optional[dict] = None, seed: int = 0,
                 **engine_kw):
        model, params = build_model(model_kind, config_kw, seed)
        self.engine = LLMEngine(model, params, **engine_kw)

    def __call__(self, request: dict) -> dict:
        rid = self.engine.submit(request["tokens"],
                                 int(request.get("max_new_tokens", 16)),
                                 request.get("eos_id"))
        return {"tokens": self.engine.result(rid, timeout=120.0)}

    def generate_batch(self, prompts, max_new_tokens: int = 16,
                       eos_id: Optional[int] = None, as_refs: bool = True):
        import ray_tpu

        if prompts and isinstance(prompts[0], ray_tpu.ObjectRef):
            prompts = ray_tpu.get_many(list(prompts))
        rids = [self.engine.submit(p, max_new_tokens, eos_id)
                for p in prompts]
        outs = [self.engine.result(r, timeout=120.0) for r in rids]
        if not as_refs:
            return outs
        return ray_tpu.put_many([np.asarray(o, np.int32) for o in outs])

    def submit_stream(self, prompt, max_new_tokens: int = 16,
                      eos_id: Optional[int] = None) -> int:
        import ray_tpu

        if isinstance(prompt, ray_tpu.ObjectRef):
            prompt = ray_tpu.get(prompt)
        return self.engine.submit(prompt, max_new_tokens, eos_id)

    def next_chunk(self, rid: int, timeout: float = 60.0):
        """Next streamed token chunk, or None when the request retired."""
        req = self.engine._requests[rid]
        try:
            return req.chunks.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"no chunk for request {rid} in {timeout}s")

    def stats(self) -> dict:
        return self.engine.stats()

    def drain(self):
        """Teardown hook: close the engine (fails in-flight requests with
        a typed error) and any replica-local batchers."""
        self.engine.close()
        from ray_tpu.serve import batching

        batching.close_instance_batchers(self)
        return True


def generate_many(handle, prompts, max_new_tokens: int = 16,
                  eos_id: Optional[int] = None,
                  timeout: float = 120.0) -> List[List[int]]:
    """Client half of the zero-copy request path: one ``put_many`` for
    the prompt batch (one coalesced control-plane notify), one actor call
    carrying refs, one ``get_many`` gather of the responses."""
    import ray_tpu

    refs = ray_tpu.put_many([np.asarray(p, np.int32) for p in prompts])
    out_refs = ray_tpu.get(
        handle.method("generate_batch").remote(refs, max_new_tokens, eos_id),
        timeout=timeout)
    return [[int(t) for t in a] for a in ray_tpu.get_many(out_refs)]
