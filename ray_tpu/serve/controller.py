"""Serve controller: the reconciliation loop that makes autoscaling real.

Reference: ServeController + DeploymentState reconciliation
(serve/controller.py:60, serve/_private/deployment_state.py:962) driven by
replica queue metrics (serve/_private/autoscaling_metrics.py) through
calculate_desired_num_replicas (autoscaling_policy.py:10-49).

Design difference: our router lives driver-side (DeploymentHandle), so the
queue metric — in-flight requests per replica — is read directly from the
handle instead of being pushed via actor gauges; the control loop is a
daemon thread in the serve process rather than a dedicated controller
actor.  The policy math and the scale-up/down mechanics match the
reference's semantics: desired = policy(current, avg_queued), replicas are
added/removed in place, downscale picks the least-loaded replica.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import ray_tpu
from ray_tpu.serve.autoscaling import calculate_desired_num_replicas


class ServeController:
    def __init__(self, interval_s: float = 1.0):
        self.interval_s = interval_s
        self._watched: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Rolling queue-metric window per deployment (smooths one-poll
        # spikes the way the reference's look_back_period does).
        self._window: Dict[str, list] = {}

    def watch(self, deployment):
        with self._lock:
            self._watched[deployment.name] = deployment
            self._window.setdefault(deployment.name, [])
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="rtpu-serve-controller",
                    daemon=True)
                self._thread.start()

    def unwatch(self, deployment):
        with self._lock:
            self._watched.pop(deployment.name, None)
            self._window.pop(deployment.name, None)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            with self._lock:
                deployments = list(self._watched.values())
            # One proxy-stats poll per tick, shared by every deployment.
            try:
                from ray_tpu.serve.api import collect_proxy_stats

                proxy_totals = collect_proxy_stats()
            except Exception:
                proxy_totals = {}
            for dep in deployments:
                try:
                    self._reconcile(dep, proxy_totals)
                except Exception:
                    import traceback

                    traceback.print_exc()

    def _reconcile(self, dep, proxy_totals=None):
        handle = dep.handle
        cfg = dep.autoscaling_config or {}
        if handle is None:
            return
        from ray_tpu.serve.api import aggregate_queue_stats

        stats = aggregate_queue_stats(dep.name, handle, proxy_totals)
        signal = stats["avg_per_replica"]
        if cfg.get("metric_method"):
            # Replica-reported load (e.g. LLMServer.autoscale_metric —
            # in-flight work per decode slot): richer than router queue
            # depth for engines that batch internally, where 8 queued
            # requests on one replica may be a full batch (scale!) or
            # an eighth of one (don't).  Best-effort: an unreachable
            # replica falls back to the queue signal for this tick.
            vals = self._poll_replica_metric(dep, cfg["metric_method"])
            if vals:
                signal = sum(vals) / len(vals)
        win = self._window.setdefault(dep.name, [])
        win.append(signal)
        look_back = max(1, int(cfg.get("look_back_polls", 3)))
        del win[:-look_back]
        avg = sum(win) / len(win)
        current = stats["num_replicas"]
        desired = calculate_desired_num_replicas(
            current_num_replicas=current,
            avg_queued_per_replica=avg,
            target_queued_per_replica=float(
                cfg.get("target_num_ongoing_requests_per_replica", 1.0)),
            min_replicas=int(cfg.get("min_replicas", 1)),
            max_replicas=int(cfg.get("max_replicas", current)),
            smoothing_factor=float(cfg.get("smoothing_factor", 1.0)))
        from ray_tpu.serve import api as serve_api

        scaled = desired != handle.num_replicas
        while desired > handle.num_replicas:
            handle.add_replica(dep._make_replica())
        doomed = []
        while desired < handle.num_replicas:
            r = handle.pop_replica()
            if r is None:
                break
            try:
                dep._replicas.remove(r)
            except ValueError:
                pass
            doomed.append(r)
        if scaled:
            # Broadcast BEFORE any kill: node proxies must stop routing
            # to a doomed replica before it dies, or their in-window
            # requests land on a corpse.
            serve_api.broadcast_routes()
        for r in doomed:
            # Graceful drain (reference: DeploymentState stops a replica
            # only after it finishes outstanding requests): routing
            # stopped at pop_replica + broadcast; wait for in-flight to
            # hit zero (driver side; proxy-side stragglers are covered by
            # the same drain window).
            deadline = time.time() + float(
                cfg.get("downscale_drain_timeout_s", 5.0))
            while handle.in_flight_of(r) > 0 and time.time() < deadline:
                time.sleep(0.05)
            handle.forget_replica(r)
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def _poll_replica_metric(self, dep, method: str):
        """One round of replica-load samples, polled concurrently with a
        bounded wait — a slow replica costs one tick's sample, never a
        stalled control loop."""
        refs = []
        for r in list(dep._replicas):
            try:
                refs.append(r.handle_request.remote(method, (), {}))
            except Exception:
                continue
        vals = []
        for ref in refs:
            try:
                vals.append(float(ray_tpu.get(ref, timeout=5)))
            except Exception:
                continue
        return vals

    def shutdown(self):
        self._stop.set()
        with self._lock:
            self._watched.clear()
            self._window.clear()


_controller: Optional[ServeController] = None


def get_controller() -> ServeController:
    global _controller
    if _controller is None:
        from ray_tpu._private.config import CONFIG

        _controller = ServeController(
            interval_s=CONFIG.serve_control_interval_s)
    return _controller


def reset_controller():
    global _controller
    if _controller is not None:
        _controller.shutdown()
        _controller = None
