"""Cluster-wide content-addressed KV prefix cache for the serving tier.

The observation that makes serving millions of users affordable: real
traffic shares prompt prefixes (system prompts, few-shot preambles,
conversation history), and the KV cache of a token prefix depends ONLY
on that prefix — attention is causal, positions are absolute, and every
replica materializes identical weights from the same seed.  So a KV
page whose token span is complete is an **immutable, content-addressed
value**: hash the token prefix that produced it and any replica may
reuse it.

Three layers, mirroring the checkpoint chunk store's design
(``checkpoint/chunks.py``: blake2b-160 content addressing, dedup by
hash) applied to device KV pages:

- :func:`page_key` — blake2b-160 over (namespace, tokens[:page_end]).
  The namespace folds in everything that changes the bytes (model
  config, init seed, page size, dtype) so two deployments can share an
  object plane without poisoning each other.
- :class:`PrefixCacheLocal` — per-replica host-memory LRU of unpacked
  pages.  Pure data structure; the engine consults it first, so a
  replica that already served a prefix pays one host→device copy
  instead of a prefill.
- :class:`PrefixDirectory` — the cluster half: a tiny actor mapping
  page key → object-plane refs (pages are published with ``put_many``
  after prefill and fetched with ``get_many`` on a remote hit — the
  PR 3 object plane is the transport, exactly as ROADMAP prescribes).
  The directory holds the refs, which keeps the published objects
  alive; eviction drops them and distributed ref-counting reclaims the
  store bytes.

**Cache-affinity routing** rides the same hashes: :func:`affinity_key`
digests the first page's worth of tokens, and the serve router
(``api.DeploymentHandle``) rendezvous-hashes that key over the live
replica set — requests sharing a prefix land on the replica already
holding those pages, with no routing state to migrate when autoscaling
changes the set.

This module stays import-light (numpy + hashlib) — no jax at module
scope — so routers and proxies can hash without touching a model.
"""
from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

# Tokens hashed for the router affinity key.  Any fixed count works (all
# parties just need to agree); one default page is a natural prefix unit.
AFFINITY_PREFIX_TOKENS = 16


def versioned_namespace(base: str, weight_version: int) -> str:
    """Fold the serving weight version into a cache namespace.

    KV pages are a pure function of (weights, token prefix): after a
    hot weight swap (``LLMEngine.swap_weights``) every page computed
    under the old weights is stale for the new policy, and a cache hit
    on one would silently splice old-policy K/V into a new-policy
    context.  Folding the version into the namespace makes every
    pre-swap key unreachable — the invalidation is by *addressing*, no
    sweep required, and pages published by replicas still on the old
    version can't poison replicas on the new one."""
    return f"{base}|wv{int(weight_version)}"


def page_key(namespace: str, tokens) -> str:
    """Content address of the KV page covering ``tokens`` — the blake2b
    idiom from ``checkpoint/chunks.py:hash_chunk`` over the *token
    prefix* (every token up to the page's end, because causal attention
    makes earlier tokens part of the page's value)."""
    h = hashlib.blake2b(digest_size=20)
    h.update(namespace.encode("utf-8"))
    h.update(b"\x00")
    h.update(np.ascontiguousarray(tokens, dtype=np.int32).tobytes())
    return h.hexdigest()


def prefix_page_keys(namespace: str, tokens, page_size: int,
                     max_pages: Optional[int] = None) -> List[str]:
    """Keys for every FULL page of ``tokens``: key i covers tokens
    ``[0, (i+1)*page_size)``.  ``max_pages`` truncates (admission caps
    at ``(len - 1) // page_size`` so the sampled next token always has
    at least one freshly-computed position behind it)."""
    toks = np.ascontiguousarray(tokens, dtype=np.int32)
    n = len(toks) // page_size
    if max_pages is not None:
        n = min(n, max_pages)
    return [page_key(namespace, toks[:(i + 1) * page_size])
            for i in range(n)]


def affinity_key(tokens, n_tokens: int = AFFINITY_PREFIX_TOKENS) -> str:
    """Stable routing key for cache-affinity: digest of the first
    ``n_tokens`` tokens (shorter prompts hash what they have)."""
    toks = np.ascontiguousarray(tokens, dtype=np.int32)[:n_tokens]
    return hashlib.blake2b(toks.tobytes(), digest_size=8).hexdigest()


def rendezvous_pick(key: str, candidates: List[str]) -> Optional[int]:
    """Index of the highest-scoring candidate under rendezvous (HRW)
    hashing — every router maps the same key to the same replica with no
    shared state, and replica-set changes only remap the keys that
    scored highest on the changed replica."""
    if not candidates:
        return None
    best, best_score = 0, b""
    for i, cand in enumerate(candidates):
        score = hashlib.blake2b((key + "|" + cand).encode("utf-8"),
                                digest_size=8).digest()
        if score > best_score:
            best, best_score = i, score
    return best


class PrefixCacheLocal:
    """Byte-bounded LRU of unpacked KV pages, host memory, thread-safe.

    Values are ``(k, v)`` numpy arrays of shape [L, page_size, Hkv, D]
    in the engine's cache dtype — exactly what the engine's page-adopt
    program scatters back onto the device, so a local hit is one H2D
    copy."""

    def __init__(self, max_bytes: int = 256 * 1024 * 1024):
        self.max_bytes = int(max_bytes)
        self._entries: "collections.OrderedDict[str, Tuple]" = \
            collections.OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: str):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0], entry[1]

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def put(self, key: str, k: np.ndarray, v: np.ndarray) -> None:
        nbytes = int(k.nbytes + v.nbytes)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[2]
            self._entries[key] = (k, v, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, (_, _, freed) = self._entries.popitem(last=False)
                self._bytes -= freed
                self.evictions += 1

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


class PrefixDirectory:
    """Cluster-wide page-key → object-plane-refs map.

    Deploy as an actor (``create_directory()``) shared by every replica
    of a deployment: publishers ``put_many`` a page's (k, v) arrays and
    register the refs here; a replica missing a prefix locally looks the
    keys up and ``get_many``s the winners.  Holding the ref objects in
    this actor keeps the published pages alive in the object plane
    (distributed ref counting); ``max_entries`` LRU-drops the oldest,
    which releases the store bytes.
    """

    def __init__(self, max_entries: int = 4096):
        self.max_entries = int(max_entries)
        self._refs: "collections.OrderedDict[str, Tuple]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._published = 0
        self._lookups = 0
        self._hits = 0

    def publish(self, key: str, refs) -> bool:
        """Register one page; ``refs`` is the [k_ref, v_ref] pair —
        NESTED in a list on purpose: a top-level ObjectRef arg would be
        materialized by the task runtime, while refs inside a value arg
        arrive as refs (and ride the contained-ref pinning that keeps
        them alive through the handoff).  Returns False on a dedup hit
        (callers drop their duplicate refs and the duplicate object is
        reclaimed)."""
        k_ref, v_ref = refs
        with self._lock:
            if key in self._refs:
                self._refs.move_to_end(key)
                return False
            self._refs[key] = (k_ref, v_ref)
            self._published += 1
            while len(self._refs) > self.max_entries:
                self._refs.popitem(last=False)
            return True

    def lookup_many(self, keys: List[str]) -> List[Optional[Tuple]]:
        """(k_ref, v_ref) per key, None on miss — one round trip for the
        whole ladder of prefix keys.  The refs ride nested inside the
        result value, so the caller receives ObjectRefs to get_many."""
        out = []
        with self._lock:
            self._lookups += len(keys)
            for key in keys:
                entry = self._refs.get(key)
                if entry is not None:
                    self._refs.move_to_end(key)
                    self._hits += 1
                out.append(entry)
        return out

    def contains_many(self, keys: List[str]) -> List[bool]:
        with self._lock:
            return [k in self._refs for k in keys]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._refs),
                    "published": self._published,
                    "lookups": self._lookups, "hits": self._hits}


def create_directory(max_entries: int = 4096):
    """Spawn a PrefixDirectory actor (requires a connected runtime).
    Pass the returned handle to every replica via deployment bind args —
    actor handles serialize, and one directory serves a deployment."""
    import ray_tpu

    actor_cls = ray_tpu.remote(PrefixDirectory)
    return actor_cls.remote(max_entries)
