"""Seeded sampling for the LLM decode engine.

Real serving is not greedy-only: temperature and nucleus (top-p)
sampling are table stakes.  The constraint that makes them compatible
with this engine's correctness machinery — recompute preemption,
speculative-decode verification, and token-identity test gates — is
**determinism**: the token sampled at absolute position ``t`` of a
request must depend only on ``(request seed, t, logits)``, never on how
the engine happened to batch or schedule the step that produced it.

The rule: ``key(t) = fold_in(PRNGKey(seed), t)`` where ``t`` is the
absolute position of the token being *generated*.  A preempted request
re-prefilled from ``prompt + generated-so-far`` resumes at the same
absolute positions, so it re-draws the exact tokens it would have
produced; a speculative verify step samples positions ``len+1..len+k``
with the same keys the plain decode loop would have used, which is what
lets the accept-longest-prefix rule emit *bitwise* the non-speculative
stream.

``temperature == 0`` selects argmax (greedy) — the engine default, and
the contract every pre-existing token-identity gate asserts.

Everything here is jit-inlinable jnp code over fixed ``[N]``/``[N, V]``
shapes, so adding sampling to the engine's compiled steps does not add
recompiles.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs.

    temperature: 0.0 = greedy argmax; > 0 softmax-temperature sampling.
    top_p: nucleus truncation — sample only from the smallest set of
        tokens whose cumulative probability reaches ``top_p`` (1.0 = no
        truncation).  Applied after temperature scaling.
    seed: the per-request PRNG seed; the token at absolute position t is
        drawn with ``fold_in(PRNGKey(seed), t)``, making decode
        deterministic across runs, schedules, and preemption-resume.
    """

    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0

    def validate(self) -> "SamplingParams":
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got "
                             f"{self.temperature}")
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        return self


GREEDY = SamplingParams()


def top_p_mask(logits, top_p):
    """Boolean [.., V] nucleus mask: True for tokens in the smallest set
    whose cumulative probability (descending order) reaches ``top_p``.

    The highest-probability token is always kept (its cumulative mass
    *before* itself is 0 < top_p), so the mask can never be empty.
    Ties are broken by sort order, which jnp.argsort makes stable —
    the numpy reference in tests mirrors it exactly.
    """
    import jax
    import jax.numpy as jnp

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    order = jnp.argsort(-probs, axis=-1)  # descending, stable
    sorted_probs = jnp.take_along_axis(probs, order, axis=-1)
    csum = jnp.cumsum(sorted_probs, axis=-1)
    # Keep a token while the mass accumulated BEFORE it is < top_p.
    keep_sorted = (csum - sorted_probs) < top_p[..., None]
    inv = jnp.argsort(order, axis=-1)
    return jnp.take_along_axis(keep_sorted, inv, axis=-1)


def sample_tokens_with_logprobs(logits, positions, temperature, top_p,
                                seeds):
    """Draw one token per row and capture its behavior logprob.  All
    jnp, fixed shapes, jit-inlinable.

    logits: [N, V] fp32; positions: [N] absolute position of the token
    being generated; temperature/top_p: [N] f32; seeds: [N] int32.
    Rows with ``temperature <= 0`` take the argmax instead (greedy and
    sampled requests share one compiled step).

    Returns ``(tokens [N] int32, logps [N] f32)``.  The logprob is the
    RAW log-softmax of the model's logits at the chosen token —
    ``log pi(token | context)`` at temperature 1 with no nucleus
    truncation — which is exactly what a full-context forward pass
    recomputes and what the PPO ratio's behavior term needs.  Sampling
    transforms (temperature, top-p) change *which* token is drawn, not
    the definition of the captured logprob, so greedy and sampled
    requests stamp comparable values.
    """
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(temperature, 1e-6)[..., None]
    scaled = logits / temp
    masked = jnp.where(top_p_mask(scaled, top_p), scaled, -jnp.inf)

    def draw(row_logits, pos, seed):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        return jax.random.categorical(key, row_logits).astype(jnp.int32)

    sampled = jax.vmap(draw)(masked, positions, seeds)
    tokens = jnp.where(temperature <= 0.0, greedy, sampled)
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    logps = jnp.take_along_axis(logp_all, tokens[..., None],
                                axis=-1)[..., 0]
    return tokens, logps


def sample_tokens(logits, positions, temperature, top_p, seeds):
    """Token-only form of :func:`sample_tokens_with_logprobs` (the
    logprob computation is dead code XLA eliminates when unused)."""
    return sample_tokens_with_logprobs(logits, positions, temperature,
                                       top_p, seeds)[0]
