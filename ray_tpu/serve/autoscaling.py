"""Autoscaling policy (reference: serve/_private/autoscaling_policy.py:10-49)."""
from __future__ import annotations

import math


def calculate_desired_num_replicas(current_num_replicas: int,
                                   avg_queued_per_replica: float,
                                   target_queued_per_replica: float = 1.0,
                                   min_replicas: int = 1,
                                   max_replicas: int = 10,
                                   smoothing_factor: float = 1.0) -> int:
    if current_num_replicas == 0:
        return min_replicas
    error_ratio = avg_queued_per_replica / max(target_queued_per_replica, 1e-9)
    desired = math.ceil(current_num_replicas
                        * (1 + (error_ratio - 1) * smoothing_factor))
    return max(min_replicas, min(max_replicas, desired))
