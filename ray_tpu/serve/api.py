"""Serve API: @deployment, run, handles, HTTP proxy."""
from __future__ import annotations

import itertools
import json
import threading
from typing import Any, Callable, Dict, List, Optional

import ray_tpu

_deployments: Dict[str, "Deployment"] = {}
_proxy = None


@ray_tpu.remote
class _Replica:
    """Hosts one copy of the user callable (reference: RayServeReplica,
    serve/_private/replica.py:260).  A replica can hold a pjit-compiled
    inference mesh — the callable owns whatever devices its worker sees."""

    def __init__(self, cls_or_fn, init_args, init_kwargs):
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self._callable = cls_or_fn
        self._queued = 0

    def handle_request(self, method: str, args, kwargs):
        target = (self._callable if method == "__call__"
                  else getattr(self._callable, method))
        if not callable(target):
            raise TypeError(f"{method} is not callable on this deployment")
        return target(*args, **kwargs)

    def queue_len(self) -> int:
        return self._queued

    def drain(self) -> bool:
        """Teardown hook: close the callable's batchers (waking blocked
        submitters with a typed error) and, if the callable exposes its
        own drain (e.g. llm_engine.LLMServer), run it — so killing the
        replica never strands callers mid-queue."""
        from ray_tpu.serve import batching

        fn = getattr(self._callable, "drain", None)
        if callable(fn):
            try:
                fn()
            except Exception:
                pass
        batching.close_instance_batchers(self._callable)
        return True

    def reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True


def _replica_key(r) -> str:
    """Stable identity of a replica actor across handle copies (the
    rendezvous-hash input for cache-affinity routing)."""
    aid = getattr(r, "_actor_id", None)
    if aid is None:
        return f"id:{id(r)}"
    try:
        return aid.hex()
    except AttributeError:
        return str(aid)


class DeploymentHandle:
    """Router over a *mutable* replica set: least-loaded assignment with an
    in-flight cap, live queue metrics for the controller, and dynamic
    add/remove so autoscaling reconfigures in place (reference:
    Router/ReplicaSet, serve/_private/router.py:62,221).

    **Cache-affinity routing**: ``remote(..., _affinity=key)`` rendezvous-
    hashes the key over the live replica ids (serve/prefix_cache.py), so
    every router — driver handle and every node proxy — sends requests
    sharing a prompt prefix to the replica already holding its cached KV
    pages, with no shared routing state and automatic remapping when
    autoscaling changes the set.  A saturated preferred replica falls
    back to the normal least-loaded path (affinity is a hint, never a
    hotspot amplifier)."""

    def __init__(self, name: str, replicas: List[Any],
                 max_in_flight_per_replica: int = 8):
        self.name = name
        self._replicas: List[Any] = list(replicas)
        self._in_flight: Dict[Any, int] = {r: 0 for r in self._replicas}
        self._rr = 0
        self._cap = max_in_flight_per_replica
        self._lock = threading.Lock()
        self._affinity_hits = 0
        self._affinity_misses = 0

    def __reduce__(self):
        # A handle serializes as a SNAPSHOT of its replica set (actor
        # handles pickle; the lock and in-flight counters are
        # per-process router state, rebuilt empty).  This is what lets
        # a deployment handle ride bind args into another deployment's
        # replicas — e.g. the decode engine's ``prefill=`` handle.  The
        # copy does not see later autoscale events (the node proxies'
        # route broadcast is the pattern for that).
        with self._lock:
            return (DeploymentHandle,
                    (self.name, list(self._replicas), self._cap))

    def remote(self, *args, _method: str = "__call__",
               _affinity: Optional[str] = None, **kwargs):
        with self._lock:
            if not self._replicas:
                raise RuntimeError(f"deployment {self.name} has no replicas")
            n = len(self._replicas)
            pick = None
            if _affinity is not None:
                from ray_tpu.serve.prefix_cache import rendezvous_pick

                i = rendezvous_pick(
                    _affinity, [_replica_key(r) for r in self._replicas])
                cand = self._replicas[i]
                if self._in_flight[cand] < self._cap:
                    pick = cand
                    self._affinity_hits += 1
                else:
                    self._affinity_misses += 1
            # Round-robin start, pick the first under-cap replica; when all
            # are saturated take the least loaded (requests queue in the
            # actor's mailbox — that queue depth is the autoscaling signal).
            if pick is None:
                for k in range(n):
                    r = self._replicas[(self._rr + k) % n]
                    if self._in_flight[r] < self._cap:
                        pick = r
                        break
            if pick is None:
                pick = min(self._replicas, key=lambda r: self._in_flight[r])
            self._rr = (self._rr + 1) % max(1, n)
            self._in_flight[pick] += 1
        ref = pick.handle_request.remote(_method, args, kwargs)

        def done(_f):
            with self._lock:
                if pick in self._in_flight:
                    self._in_flight[pick] -= 1

        try:
            ref.future().add_done_callback(done)
        except Exception:
            with self._lock:
                if pick in self._in_flight:
                    self._in_flight[pick] -= 1
        return ref

    def method(self, name: str):
        h = self

        class _M:
            def remote(self, *a, **kw):
                return h.remote(*a, _method=name, **kw)

        return _M()

    # ---- controller surface ----
    def queue_stats(self) -> Dict[str, float]:
        """Total and per-replica in-flight load (the metric the reference's
        replicas push to the controller, serve/_private/autoscaling_metrics)."""
        with self._lock:
            total = sum(self._in_flight.values())
            n = max(1, len(self._replicas))
            return {"total_in_flight": float(total),
                    "avg_per_replica": total / n,
                    "num_replicas": len(self._replicas),
                    "affinity_hits": float(self._affinity_hits),
                    "affinity_misses": float(self._affinity_misses)}

    def add_replica(self, replica):
        with self._lock:
            self._replicas.append(replica)
            self._in_flight[replica] = 0

    def set_replicas(self, replicas):
        """Swap the replica set IN PLACE, matching by actor id: retained
        replicas keep their handle objects (so outstanding requests'
        done-callbacks still decrement the live counters — a rebuilt
        handle would zero the autoscaling signal on every broadcast)."""
        with self._lock:
            by_id = {r._actor_id: r for r in self._replicas}
            new_list = []
            for r in replicas:
                existing = by_id.pop(getattr(r, "_actor_id", None), None)
                if existing is not None:
                    new_list.append(existing)
                else:
                    new_list.append(r)
                    self._in_flight[r] = 0
            self._replicas = new_list
            for gone in by_id.values():
                self._in_flight.pop(gone, None)

    def pop_replica(self):
        """Remove (and return) the least-loaded replica, or None at size 1.

        Routing stops immediately, but the in-flight counter entry is KEPT
        so outstanding requests keep decrementing it — the controller
        drains on in_flight_of() before killing, then forget_replica()."""
        with self._lock:
            if len(self._replicas) <= 1:
                return None
            r = min(self._replicas, key=lambda x: self._in_flight[x])
            self._replicas.remove(r)
            return r

    def in_flight_of(self, replica) -> int:
        with self._lock:
            return self._in_flight.get(replica, 0)

    def forget_replica(self, replica):
        with self._lock:
            self._in_flight.pop(replica, None)

    @property
    def num_replicas(self):
        with self._lock:
            return len(self._replicas)


class Deployment:
    def __init__(self, cls_or_fn, name: str, num_replicas: int = 1,
                 ray_actor_options: Optional[dict] = None,
                 user_config: Any = None,
                 autoscaling_config: Optional[dict] = None):
        self._func = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.ray_actor_options = ray_actor_options or {}
        self.user_config = user_config
        self.autoscaling_config = autoscaling_config
        self._init_args: tuple = ()
        self._init_kwargs: dict = {}
        self.handle: Optional[DeploymentHandle] = None
        self._replicas: List[Any] = []

    def bind(self, *args, **kwargs) -> "Deployment":
        self._init_args = args
        self._init_kwargs = kwargs
        return self

    def options(self, **kw) -> "Deployment":
        import copy

        d = copy.copy(self)
        # The shallow copy must not alias the replica list — a teardown of
        # one deployment would otherwise kill its siblings' replicas.
        d._replicas = []
        d.handle = None
        for k, v in kw.items():
            setattr(d, k, v)
        return d

    # ---- lifecycle ----
    def _make_replica(self):
        opts = dict(self.ray_actor_options)
        opts.setdefault("max_concurrency", 8)
        r = _Replica.options(**opts).remote(self._func, self._init_args,
                                            self._init_kwargs)
        if self.user_config is not None:
            ray_tpu.get(r.reconfigure.remote(self.user_config))
        self._replicas.append(r)
        return r

    def _deploy(self) -> DeploymentHandle:
        self._replicas = []
        start = self.num_replicas
        if self.autoscaling_config:
            start = max(int(self.autoscaling_config.get("min_replicas", 1)),
                        min(start, int(self.autoscaling_config.get(
                            "max_replicas", start))))
        replicas = [self._make_replica() for _ in range(start)]
        self.handle = DeploymentHandle(self.name, replicas)
        if self.autoscaling_config:
            from ray_tpu.serve.controller import get_controller

            get_controller().watch(self)
        return self.handle

    def _teardown(self):
        from ray_tpu.serve.controller import get_controller

        get_controller().unwatch(self)
        # Drain before kill: close each replica's batchers so submitters
        # blocked on a batcher future get a typed BatcherClosedError
        # instead of hanging on a killed actor forever.
        acks = []
        for r in self._replicas:
            try:
                acks.append(r.drain.remote())
            except Exception:
                pass
        for a in acks:
            try:
                ray_tpu.get(a, timeout=5)
            except Exception:
                pass
        for r in self._replicas:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._replicas = []


def deployment(_func=None, *, name: Optional[str] = None,
               num_replicas: int = 1, ray_actor_options: Optional[dict] = None,
               user_config: Any = None,
               autoscaling_config: Optional[dict] = None):
    """@serve.deployment (reference: serve/api.py:251)."""

    def wrap(cls_or_fn):
        return Deployment(cls_or_fn, name or cls_or_fn.__name__,
                          num_replicas, ray_actor_options, user_config,
                          autoscaling_config)

    if _func is not None:
        return wrap(_func)
    return wrap


def run(dep: Deployment, name: Optional[str] = None) -> DeploymentHandle:
    """serve.run (reference: serve/api.py:455)."""
    key = name or dep.name
    old = _deployments.pop(key, None)
    if old is not None:
        # Unroute everywhere FIRST (proxies briefly 404 the name), then
        # free the old replicas' resources before deploying the new ones
        # — deploy-before-teardown would deadlock a redeploy whose old
        # replicas hold resources the new ones need, and broadcast-after-
        # kill would route proxies at corpses.
        broadcast_routes()
        old._teardown()
    try:
        handle = dep._deploy()
    except BaseException:
        if old is not None:
            # Roll back: a failed redeploy must not leave a previously
            # healthy name with zero replicas.
            try:
                old._deploy()
                _deployments[key] = old
                broadcast_routes()
            except Exception:
                pass
        raise
    _deployments[key] = dep
    broadcast_routes()
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    return _deployments[name].handle


def delete(name: str):
    dep = _deployments.pop(name, None)
    # Unroute everywhere first, then kill.
    broadcast_routes()
    if dep is not None:
        dep._teardown()


def shutdown():
    global _proxy
    for name in list(_deployments):
        delete(name)
    # Driver-process batchers (plain-function @serve.batch, local-mode
    # replicas): close them here — their daemon threads and any blocked
    # submitters don't die with a remote actor.
    from ray_tpu.serve import batching

    batching.shutdown_batchers()
    if _proxy is not None:
        _proxy.shutdown()
        _proxy = None
    with _proxy_lock:
        doomed = _node_proxies + _demoted_proxies
        _node_proxies.clear()
        _demoted_proxies.clear()
        _proxy_strikes.clear()
    for p in doomed:
        try:
            ray_tpu.kill(p)
        except Exception:
            pass
    from ray_tpu.serve.controller import reset_controller

    reset_controller()


def _make_http_handler(resolve):
    """HTTP handler class over a route resolver: ``resolve(name)`` →
    (DeploymentHandle, is_ingress) or None.  The driver proxy resolves
    against the live ``_deployments`` registry; per-node proxy ACTORS
    resolve against their broadcast route table — one handler, two
    routers (reference: HTTPProxy's shared request path,
    serve/_private/http_proxy.py:230)."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def _route(self):
            from urllib.parse import urlsplit

            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            split = urlsplit(self.path)
            # Name comes from the PATH only — '/echo?x=1' must route
            # to 'echo', not 404 on a name containing the query.
            name = split.path.strip("/").split("/")[0]
            resolved = resolve(name)
            if resolved is None:
                self.send_response(404)
                self.end_headers()
                self.wfile.write(b'{"error": "no such deployment"}')
                return
            handle, is_ingress = resolved
            if is_ingress:
                # ASGI path: ship the full request dict; the replica
                # drives the app and returns {status, headers, body}.
                sub = split.path[len(name) + 1:] or "/"
                req = {"method": self.command, "path": sub,
                       "query_string": split.query,
                       "headers": list(self.headers.items()),
                       "body": body}
                try:
                    resp = ray_tpu.get(handle.remote(req))
                except Exception as e:  # noqa: BLE001
                    out = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                    return
                payload = resp.get("body") or b""
                self.send_response(resp.get("status", 200))
                hdrs = resp.get("headers") or []
                hdrs = hdrs.items() if isinstance(hdrs, dict) else hdrs
                for k, v in hdrs:
                    if k.lower() != "content-length":
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return
            if self.command != "POST":
                # Plain JSON deployments keep the POST-only contract:
                # stray GETs (crawlers, health checks) must not invoke
                # user code with a None payload.
                self.send_response(405)
                self.end_headers()
                self.wfile.write(b'{"error": "POST only"}')
                return
            try:
                payload = json.loads(body) if body else None
                affinity = None
                if isinstance(payload, dict) and payload.get("tokens"):
                    # LLM-shaped request: route by prompt-prefix affinity
                    # so shared prefixes land on the replica that cached
                    # their KV pages.
                    from ray_tpu.serve.prefix_cache import affinity_key

                    affinity = affinity_key(payload["tokens"])
                result = ray_tpu.get(handle.remote(payload,
                                                   _affinity=affinity))
                out = json.dumps({"result": result}).encode()
                self.send_response(200)
            except Exception as e:  # noqa: BLE001
                out = json.dumps({"error": str(e)}).encode()
                self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        do_POST = do_GET = do_PUT = do_DELETE = do_PATCH = _route

        def log_message(self, *a):
            pass

    return Handler


def _driver_resolve(name: str):
    dep = _deployments.get(name)
    if dep is None or dep.handle is None:
        return None
    return dep.handle, bool(getattr(dep, "is_ingress", False))


class _HttpProxy:
    """Threaded stdlib HTTP server forwarding POST /<deployment> bodies
    (JSON) to handles (reference: HTTPProxy ASGI actor)."""

    def __init__(self, port: int, resolve=None, bind: str = "127.0.0.1"):
        import http.server

        handler = _make_http_handler(resolve or _driver_resolve)
        self.server = http.server.ThreadingHTTPServer((bind, port), handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def shutdown(self):
        self.server.shutdown()


@ray_tpu.remote
class HTTPProxyActor:
    """Per-node HTTP ingress (reference: one HTTPProxy actor per node,
    serve/_private/http_proxy.py:230).  Routes against a broadcast table
    of replica actor handles — the driver pushes updates on every deploy/
    delete/autoscale event, so all node proxies serve one coherent route
    table while keeping their in-flight accounting local (the reference's
    routers are also proxy-local)."""

    def __init__(self, port: int = 0, bind: str = "0.0.0.0"):
        self._routes: Dict[str, DeploymentHandle] = {}
        self._ingress: Dict[str, bool] = {}
        self._lock = threading.Lock()

        def resolve(name):
            with self._lock:
                h = self._routes.get(name)
                if h is None:
                    return None
                return h, self._ingress.get(name, False)

        self._proxy = _HttpProxy(port, resolve=resolve, bind=bind)

    def ready(self) -> int:
        return self._proxy.port

    def update_routes(self, routes: Dict[str, dict]) -> bool:
        """routes: {name: {"replicas": [actor handles], "is_ingress": b}}.
        Existing handles update in place (set_replicas) so in-flight
        counters — the autoscaling signal — survive a broadcast."""
        with self._lock:
            new_routes: Dict[str, DeploymentHandle] = {}
            for name, r in routes.items():
                h = self._routes.get(name)
                if h is None:
                    h = DeploymentHandle(name, r["replicas"])
                else:
                    h.set_replicas(r["replicas"])
                new_routes[name] = h
            self._routes = new_routes
            self._ingress = {name: bool(r.get("is_ingress"))
                             for name, r in routes.items()}
        return True

    def queue_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-deployment in-flight load at THIS proxy — the autoscaling
        signal the controller aggregates across proxies (reference: the
        replicas' autoscaling metric push, autoscaling_metrics.py)."""
        with self._lock:
            return {name: h.queue_stats()
                    for name, h in self._routes.items()}


_node_proxies: List[Any] = []
_demoted_proxies: List[Any] = []
_proxy_strikes: Dict[str, int] = {}
# One lock for the three structures above: the controller loop, a
# concurrent broadcast_routes() (deploy from another thread) and shutdown()
# all mutate them; unsynchronized list surgery loses strikes or double-
# demotes.  Strikes are keyed by the proxy's stable actor id — handle
# objects for the same actor may differ (deserialized copies), and id() of
# a dead handle can be recycled by the allocator.
_proxy_lock = threading.Lock()
_PROXY_MAX_STRIKES = 3


def _proxy_key(p) -> str:
    aid = getattr(p, "_actor_id", None)
    if aid is not None:
        try:
            return aid.hex()
        except AttributeError:
            return str(aid)
    return f"id:{id(p)}"


def _proxy_ok(p):
    with _proxy_lock:
        _proxy_strikes.pop(_proxy_key(p), None)


def _proxy_failed(p):
    """Strike a proxy; after 3 consecutive failures DEMOTE it — its RPC
    timeout must not stall every controller poll, but a merely-slow
    proxy on a live node keeps its listening socket and still receives
    best-effort route broadcasts (a successful broadcast ack promotes it
    back); killing it would turn three slow polls into a permanent
    ingress outage for that node."""
    key = _proxy_key(p)
    with _proxy_lock:
        n = _proxy_strikes.get(key, 0) + 1
        _proxy_strikes[key] = n
        if n >= _PROXY_MAX_STRIKES:
            try:
                _node_proxies.remove(p)
            except ValueError:
                pass
            if p not in _demoted_proxies:
                _demoted_proxies.append(p)
            _proxy_strikes.pop(key, None)


def start_http_proxy(port: int = 0) -> int:
    """Start the driver-local HTTP ingress; returns the bound port."""
    global _proxy
    if _proxy is None:
        _proxy = _HttpProxy(port)
    return _proxy.port


def start_http_proxies(port: int = 0) -> Dict[str, int]:
    """Per-node ingress (reference: ProxyLocation.EveryNode): one
    HTTPProxyActor pinned to EACH cluster node, all serving the same
    route table.  Returns {node_id_hex: bound_port}."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    global _node_proxies
    nodes = [n["node_id"] for n in ray_tpu.nodes() if n.get("alive", True)]
    out = {}
    for node_hex in nodes:
        actor = HTTPProxyActor.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_hex),
            max_concurrency=16).remote(port)
        out[node_hex] = ray_tpu.get(actor.ready.remote())
        with _proxy_lock:
            _node_proxies.append(actor)
    broadcast_routes()
    return out


def _current_routes() -> Dict[str, dict]:
    return {name: {"replicas": list(dep._replicas),
                   "is_ingress": bool(getattr(dep, "is_ingress", False))}
            for name, dep in _deployments.items()
            if dep.handle is not None}


def collect_proxy_stats() -> Dict[str, float]:
    """ONE stats RPC per proxy per controller tick (shared across every
    watched deployment): {deployment: summed in-flight across proxies}.
    A proxy failing the poll takes exactly one strike per tick."""
    totals: Dict[str, float] = {}
    with _proxy_lock:
        healthy = list(_node_proxies)
    for p in healthy:
        try:
            pstats = ray_tpu.get(p.queue_stats.remote(), timeout=5)
            _proxy_ok(p)
        except Exception:
            _proxy_failed(p)
            continue
        for name, s in pstats.items():
            totals[name] = totals.get(name, 0.0) \
                + s.get("total_in_flight", 0.0)
    return totals


def aggregate_queue_stats(name: str, handle: DeploymentHandle,
                          proxy_totals: Optional[Dict[str, float]] = None
                          ) -> Dict[str, float]:
    """Cluster-wide queue metric for one deployment: the driver handle's
    local in-flight plus every node proxy's — requests entering through
    per-node ingress must drive autoscaling exactly like driver-side
    calls.  Pass ``proxy_totals`` (collect_proxy_stats) to share one
    poll across deployments."""
    if proxy_totals is None:
        proxy_totals = collect_proxy_stats()
    stats = handle.queue_stats()
    total = stats["total_in_flight"] + proxy_totals.get(name, 0.0)
    n = max(1, handle.num_replicas)
    return {"total_in_flight": float(total),
            "avg_per_replica": total / n,
            "num_replicas": handle.num_replicas}


def broadcast_routes() -> None:
    """Push the deployment→replicas table to every node proxy (called on
    deploy/delete and by the controller after autoscale events).  Waits
    for the acks: serve.run() returning must mean every ingress routes
    the new deployment."""
    with _proxy_lock:
        healthy_snap = list(_node_proxies)
        demoted_snap = list(_demoted_proxies)
    if not healthy_snap:
        return
    routes = _current_routes()
    acks = []
    for p in healthy_snap:
        try:
            acks.append((p, False, p.update_routes.remote(routes)))
        except Exception:
            _proxy_failed(p)
    for p in demoted_snap:
        try:
            acks.append((p, True, p.update_routes.remote(routes)))
        except Exception:
            pass
    for p, demoted, a in acks:
        try:
            ray_tpu.get(a, timeout=10)
            if demoted:
                # The proxy answered again: back into the healthy pool.
                with _proxy_lock:
                    try:
                        _demoted_proxies.remove(p)
                    except ValueError:
                        pass
                    if p not in _node_proxies:
                        _node_proxies.append(p)
            _proxy_ok(p)
        except Exception:
            if not demoted:
                _proxy_failed(p)
