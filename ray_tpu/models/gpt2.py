"""GPT-2 in flax, TPU-first.

Flagship model for the Train/Data north-star config ("GPT-2 125M language
modeling with streaming Dataset shards", BASELINE.json).  The reference has
no GPT-2 implementation — its benchmark uses HuggingFace torch through
TorchTrainer (python/ray/train/huggingface/) — so this is a ground-up
design:

- bfloat16 activations, fp32 params/optimizer (mixed precision via `dtype`),
- attention through ray_tpu.ops (Pallas flash on TPU, XLA fallback, or ring
  attention over a `sequence` mesh axis for long context),
- logical sharding axes per parameter (embed/heads/mlp/vocab) so the same
  module runs 1-chip, DP, FSDP, or DP×TP via ShardingRules,
- static shapes + scan-free layer stack (12 layers unrolls fine; a
  lax.scan-over-layers variant kicks in above `scan_layers_threshold` to
  bound compile time for deep configs).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import cached_attention, mha_attention
from ray_tpu.ops.layers import gelu


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_position_embeddings: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    use_flash: Optional[bool] = None  # None = auto by backend
    scan_layers_threshold: int = 24
    # Mixture-of-Experts: replace every block's dense MLP with a top-k
    # routed expert MLP (ray_tpu.ops.moe).  The dense-dispatch einsums
    # partition over the `expert` mesh axis under pjit via the logical
    # axes below (net-new TPU scope, SURVEY §2.4 EP).
    moe: Optional[Any] = None  # ops.moe.MoEConfig

    @classmethod
    def gpt2_small(cls, **kw):  # 125M
        return cls(**kw)

    @classmethod
    def moe_tiny(cls, num_experts: int = 8, top_k: int = 2, **kw):
        from ray_tpu.ops.moe import MoEConfig

        kw.setdefault("moe", MoEConfig(num_experts=num_experts, top_k=top_k))
        return cls.tiny(**kw)

    @classmethod
    def gpt2_medium(cls, **kw):  # 350M
        return cls(num_layers=24, num_heads=16, hidden_size=1024, **kw)

    @classmethod
    def gpt2_xl(cls, **kw):  # 1.5B — the MPMD pipeline scale target
        return cls(num_layers=48, num_heads=25, hidden_size=1600, **kw)

    @classmethod
    def tiny(cls, **kw):  # test-sized
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 2)
        kw.setdefault("hidden_size", 64)
        return cls(**kw)

    @classmethod
    def draft_of(cls, target: "GPT2Config", num_layers: int = 1,
                 num_heads: Optional[int] = None,
                 hidden_size: Optional[int] = None, **kw):
        """A speculative-decoding draft config for ``target``: shares
        the vocab, context length and dtype (the engine's hard
        requirements — serve/llm_engine.py), shrinks everything else.
        Defaults to one layer at half width, the \"tiny draft\" shape
        whose proposal cost is a small fraction of one target step."""
        heads = num_heads or max(1, target.num_heads // 2)
        hidden = hidden_size or max(heads * 8, target.hidden_size // 2)
        hidden -= hidden % heads  # head_dim must divide
        return cls(vocab_size=target.vocab_size,
                   max_position_embeddings=target.max_position_embeddings,
                   num_layers=num_layers, num_heads=heads,
                   hidden_size=hidden, dtype=target.dtype, **kw)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class Block(nn.Module):
    config: GPT2Config
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, kv=None):
        """kv = (k_cache, v_cache, lengths) switches the block to the
        incremental-decode path: attention runs against the cached prefix
        and the block ALSO returns this step's (k, v) projections so the
        caller (serve/llm_engine.py) can write them into its page pool —
        the cache layout is the engine's concern, not the model's."""
        c = self.config
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_1")(x)
        qkv = nn.Dense(3 * c.hidden_size, dtype=c.dtype, name="attn_qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, l, _ = q.shape
        q = q.reshape(b, l, c.num_heads, c.head_dim)
        k = k.reshape(b, l, c.num_heads, c.head_dim)
        v = v.reshape(b, l, c.num_heads, c.head_dim)
        if kv is not None:
            k_cache, v_cache, lengths = kv
            attn = cached_attention(q, k, v, k_cache, v_cache, lengths)
        elif self.attn_fn is not None:
            attn = self.attn_fn(q, k, v)
        else:
            attn = mha_attention(q, k, v, causal=True, use_flash=c.use_flash)
        attn = attn.reshape(b, l, c.hidden_size)
        x = x + nn.Dense(c.hidden_size, dtype=c.dtype, name="attn_proj")(attn)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_2")(x)
        if c.moe is not None:
            from ray_tpu.ops.moe import moe_apply

            d, f, e = c.hidden_size, c.mlp_ratio * c.hidden_size, \
                c.moe.num_experts
            w_router = self.param("moe_router",
                                  nn.initializers.normal(0.02), (d, e),
                                  jnp.float32)
            w_in = self.param("moe_w_in", nn.initializers.normal(0.02),
                              (e, d, f), jnp.float32)
            w_out = self.param("moe_w_out", nn.initializers.normal(0.02),
                               (e, f, d), jnp.float32)
            bsz, l, _ = h.shape
            flat = h.reshape(bsz * l, d)
            out = moe_apply(flat, w_router, w_in, w_out, c.moe)
            x = x + out.reshape(bsz, l, d).astype(c.dtype)
        else:
            h = nn.Dense(c.mlp_ratio * c.hidden_size, dtype=c.dtype,
                         name="mlp_fc")(h)
            h = gelu(h)
            x = x + nn.Dense(c.hidden_size, dtype=c.dtype, name="mlp_proj")(h)
        if kv is not None:
            return x, (k, v)
        return x


class GPT2(nn.Module):
    config: GPT2Config
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids: jax.Array, positions: jax.Array = None,
                 kv_caches=None, kv_lengths: jax.Array = None,
                 return_hidden: bool = False):
        """Training/full-context: input_ids [B, L] int32 → logits
        [B, L, vocab] (unchanged contract).  ``return_hidden=True``
        (full-context only) additionally returns the post-ln_f hidden
        states [B, L, hidden] — the value head's input in the RLHF
        stack (:class:`GPT2WithValue`).

        Incremental decode (``kv_caches`` given): ``positions`` [B, L]
        are the absolute positions of the new tokens, ``kv_caches`` is a
        per-layer list of (k, v) each [B, S, H, D] of which the first
        ``kv_lengths[b]`` rows are valid; returns (logits, new_kvs) where
        new_kvs is the per-layer list of this call's (k, v) projections
        [B, L, H, D] for the caller to append to its cache."""
        c = self.config
        b, l = input_ids.shape
        decode = kv_caches is not None
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (c.vocab_size, c.hidden_size), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (c.max_position_embeddings, c.hidden_size), jnp.float32)
        pos = wpe[None, :l] if positions is None else wpe[positions]
        x = wte[input_ids].astype(c.dtype) + pos.astype(c.dtype)
        new_kvs = []
        if c.num_layers >= c.scan_layers_threshold:
            if decode:
                raise NotImplementedError(
                    "incremental decode is unrolled-layers only; lower "
                    "scan_layers_threshold applies to training compiles")
            block = nn.remat(Block)
            ScanBlocks = nn.scan(
                block, variable_axes={"params": 0}, split_rngs={"params": True},
                length=c.num_layers, metadata_params={"partition_name": "layers"})
            x, _ = ScanBlocks(c, self.attn_fn, name="h_scan")(x, None)
        else:
            for i in range(c.num_layers):
                if decode:
                    x, nkv = Block(c, self.attn_fn, name=f"h_{i}")(
                        x, kv=(kv_caches[i][0], kv_caches[i][1], kv_lengths))
                    new_kvs.append(nkv)
                else:
                    x = Block(c, self.attn_fn, name=f"h_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        # Tied LM head: the matmul runs at the compute dtype (bf16 doubles
        # MXU rate on the single biggest matmul in the model); the logits
        # are promoted to fp32 so the downstream log-softmax keeps full
        # precision where it matters.
        logits = jnp.einsum("bld,vd->blv", x.astype(c.dtype),
                            wte.astype(c.dtype))
        logits = logits.astype(jnp.float32)
        if decode:
            if return_hidden:
                raise NotImplementedError(
                    "return_hidden is a full-context (training) path")
            return logits, new_kvs
        if return_hidden:
            return logits, x
        return logits


class GPT2WithValue(nn.Module):
    """GPT-2 plus a scalar value head — the RLHF actor-critic.

    The policy half is a plain :class:`GPT2` submodule named ``lm``, so
    ``params["lm"]`` is EXACTLY the param tree a serving
    ``LLMEngine``/``NaiveLM`` built on the same config accepts: the
    RLHF learner trains this module and hot-swaps ``params["lm"]`` into
    the generation engine with no renaming or surgery.  The value head
    is one fp32 linear over the post-ln_f hidden states (the standard
    PPO-for-LLMs shape), initialized near zero so early value estimates
    don't swamp the policy gradient.

    ``__call__(input_ids) -> (logits [B, L, V] f32, values [B, L] f32)``
    where ``values[:, t]`` estimates the return from the state AFTER
    consuming token t — the baseline for the token sampled at t+1.
    """

    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids: jax.Array):
        logits, hidden = GPT2(self.config, name="lm")(
            input_ids, return_hidden=True)
        v = nn.Dense(1, dtype=jnp.float32, name="value_head",
                     kernel_init=nn.initializers.normal(0.01))(
            hidden.astype(jnp.float32))
        return logits, v[..., 0]

    def init_from_lm(self, rng, lm_params, example_len: int = 8):
        """Params with the ``lm`` subtree REPLACED by ``lm_params`` —
        the RLHF entry point: start the actor-critic from the exact
        weights the serving engine already holds (the value head alone
        is freshly initialized)."""
        ids = jnp.zeros((1, example_len), jnp.int32)
        params = self.init(rng, ids)["params"]
        params = dict(params)
        params["lm"] = lm_params
        return params


def gpt2_loss_fn(params, apply_fn, batch) -> jax.Array:
    """Next-token cross-entropy. batch: {"input_ids": [B, L]} (labels are the
    shifted inputs, standard LM objective)."""
    ids = batch["input_ids"]
    logits = apply_fn({"params": params}, ids)[:, :-1]
    labels = ids[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


class GPT2Stage(nn.Module):
    """One pipeline stage of a split GPT-2 (see :func:`split_stages`).

    Stage 0 owns the embeddings (wte/wpe) and consumes token ids; middle
    stages consume/produce hidden states; the last stage owns ln_f and
    the LM head and produces logits.  The head is UNTIED from wte —
    pipeline splitting puts them on different processes, and the
    tied-embedding gradient exchange (Megatron's first↔last allreduce)
    costs more than the head's extra parameters buy (documented in
    docs/PERFORMANCE.md)."""

    config: GPT2Config
    first: bool
    last: bool
    blocks: tuple  # (start, stop) block index range owned by this stage

    @nn.compact
    def __call__(self, x):
        c = self.config
        if self.first:
            ids = x
            _, l = ids.shape
            wte = self.param("wte", nn.initializers.normal(0.02),
                             (c.vocab_size, c.hidden_size), jnp.float32)
            wpe = self.param("wpe", nn.initializers.normal(0.01),
                             (c.max_position_embeddings, c.hidden_size),
                             jnp.float32)
            x = wte[ids].astype(c.dtype) + wpe[None, :l].astype(c.dtype)
        else:
            x = x.astype(c.dtype)
        for i in range(*self.blocks):
            x = Block(c, name=f"h_{i}")(x)
        if self.last:
            x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
            head = self.param("lm_head", nn.initializers.normal(0.02),
                              (c.vocab_size, c.hidden_size), jnp.float32)
            logits = jnp.einsum("bld,vd->blv", x.astype(c.dtype),
                                head.astype(c.dtype))
            return logits.astype(jnp.float32)
        return x


def _stage_ce_loss(logits: jax.Array, ids: jax.Array) -> jax.Array:
    """Next-token CE on a microbatch (same objective as gpt2_loss_fn)."""
    logits = logits[:, :-1]
    labels = ids[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def gpt2_head_cost(config: GPT2Config) -> float:
    """LM-head cost in block-equivalents: a GPT-2 block is ~12*h^2
    params/FLOP-units, the head matmul vocab*h."""
    return config.vocab_size / (12.0 * config.hidden_size)


def split_stages(config: GPT2Config, num_stages: int, *,
                 virtual_per_rank: int = 1,
                 boundary_dtype: Any = jnp.float32, seed: int = 0):
    """Split a GPT-2 config into ``num_stages * virtual_per_rank``
    pipeline chunks for
    :class:`ray_tpu.parallel.mpmd_pipeline.MPMDPipeline`.

    Blocks are partitioned by COST, not count
    (``models/pipeline_split.py``): the embedding lookup is nearly free
    but the LM-head matmul costs ~``vocab/(12*hidden)`` block-equivalents
    (5+ blocks for GPT-2 vocab at small/XL widths), so the head-owning
    chunk gets proportionally fewer blocks.  With ``virtual_per_rank=v``
    the chunks interleave over the stages (chunk c on stage ``c % S``):
    the embedding stays pinned to stage 0 and the head to the last
    stage.  Returns ``(stage_fns, init_fns)`` in GLOBAL chunk order:
    ``stage_fns[c](params, x[, target])`` with the last returning the
    scalar loss, and ``init_fns[c]()`` building that chunk's params on
    the caller (run them ON the stage actors so XL-scale params never
    visit the driver).  Activations cross chunk boundaries as
    ``boundary_dtype`` (fp32 by default: bf16 objects are shippable but
    fp32 keeps the cotangent math bit-stable on CPU)."""
    from ray_tpu.models.pipeline_split import balance_chunks, chunk_flags

    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    C = num_stages * max(1, int(virtual_per_rank))
    bounds = balance_chunks(config.num_layers, C, embed_cost=0.3,
                            head_cost=gpt2_head_cost(config))

    stage_fns, init_fns = [], []
    for k, (first, last) in enumerate(chunk_flags(C)):
        module = GPT2Stage(config, first=first, last=last, blocks=bounds[k])

        if last:
            def fn(params, x, target, _m=module):
                logits = _m.apply({"params": params}, x)
                return _stage_ce_loss(logits, target)
        else:
            def fn(params, x, _m=module, _bd=boundary_dtype):
                return _m.apply({"params": params}, x).astype(_bd)

        def init_fn(_m=module, _first=first, _seed=seed + k,
                    _c=config):
            dummy = jnp.zeros((1, 8), jnp.int32) if _first else \
                jnp.zeros((1, 8, _c.hidden_size), _c.dtype)
            return _m.init(jax.random.PRNGKey(_seed), dummy)["params"]

        stage_fns.append(fn)
        init_fns.append(init_fn)
    return stage_fns, init_fns


# Logical sharding axes per parameter name suffix (DP/FSDP/TP ready).
_AXIS_BY_NAME: Dict[str, tuple] = {
    "wte": ("vocab", "embed"),
    "wpe": (None, "embed"),
    "attn_qkv/kernel": ("embed", "heads"),   # fused qkv: shard output dim
    "attn_qkv/bias": ("heads",),
    "attn_proj/kernel": ("heads", "embed_fsdp"),
    "attn_proj/bias": (None,),
    "mlp_fc/kernel": ("embed", "mlp"),
    "mlp_fc/bias": ("mlp",),
    "mlp_proj/kernel": ("mlp", "embed_fsdp"),
    "mlp_proj/bias": (None,),
    "moe_router": ("embed", None),
    "moe_w_in": ("expert", "embed", "mlp"),
    "moe_w_out": ("expert", "mlp", "embed_fsdp"),
    # Llama family (models/llama.py) — same logical axes, llama names.
    "embed/embedding": ("vocab", "embed"),
    "q_proj/kernel": ("embed", "heads"),
    "k_proj/kernel": ("embed", "heads"),
    "v_proj/kernel": ("embed", "heads"),
    "o_proj/kernel": ("heads", "embed_fsdp"),
    "gate_proj/kernel": ("embed", "mlp"),
    "up_proj/kernel": ("embed", "mlp"),
    "down_proj/kernel": ("mlp", "embed_fsdp"),
    "lm_head/kernel": ("embed", "vocab"),
}


def param_logical_axes(params) -> Any:
    """Pytree of logical-axis tuples matching `params` (None = replicate)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def axes_for(path) -> Optional[tuple]:
        name = "/".join(getattr(k, "key", str(k)) for k in path)
        for suffix, axes in _AXIS_BY_NAME.items():
            if name.endswith(suffix):
                return axes
        return None

    leaves = [axes_for(path) for path, _ in flat]
    treedef = jax.tree_util.tree_structure(
        params, is_leaf=lambda x: hasattr(x, "shape"))
    return jax.tree_util.tree_unflatten(treedef, leaves)
