"""Plain MLP (test/bench workhorse; RL policy trunk equivalent of RLlib's
fcnet, rllib/models/torch/fcnet.py)."""
from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (64, 64)
    out_dim: int = 1
    activation: Callable = nn.tanh
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            x = self.activation(nn.Dense(f, dtype=self.dtype,
                                         name=f"dense_{i}")(x))
        return nn.Dense(self.out_dim, dtype=self.dtype, name="out")(x)
