"""Nature-DQN CNN trunk for pixel RL (equivalent of RLlib's visionnet,
rllib/models/torch/visionnet.py).  NHWC, bfloat16-friendly."""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class NatureCNN(nn.Module):
    out_dim: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        """x: [B, H, W, C] uint8 or float → [B, out_dim]."""
        if x.dtype == jnp.uint8:
            x = x.astype(self.dtype) / 255.0
        else:
            x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (8, 8), strides=(4, 4), dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (4, 4), strides=(2, 2), dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), strides=(1, 1), dtype=self.dtype)(x))
        x = x.reshape((x.shape[0], -1))
        return nn.relu(nn.Dense(self.out_dim, dtype=self.dtype)(x))


class MinAtarCNN(nn.Module):
    """Small-grid pixel trunk (10x10-class boards): the 84x84 Nature stack's
    8x8/4 stride degenerates below ~32px, so small boards get one 3x3
    conv + dense, the standard MinAtar-scale architecture."""

    out_dim: int = 128
    features: int = 16
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(self.features, (3, 3), dtype=self.dtype)(x))
        x = x.reshape((x.shape[0], -1))
        return nn.relu(nn.Dense(self.out_dim, dtype=self.dtype)(x))
