"""Nature-DQN CNN trunk for pixel RL (equivalent of RLlib's visionnet,
rllib/models/torch/visionnet.py).  NHWC, bfloat16-friendly."""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class NatureCNN(nn.Module):
    out_dim: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        """x: [B, H, W, C] uint8 or float → [B, out_dim]."""
        if x.dtype == jnp.uint8:
            x = x.astype(self.dtype) / 255.0
        else:
            x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (8, 8), strides=(4, 4), dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (4, 4), strides=(2, 2), dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), strides=(1, 1), dtype=self.dtype)(x))
        x = x.reshape((x.shape[0], -1))
        return nn.relu(nn.Dense(self.out_dim, dtype=self.dtype)(x))
