"""Llama-family decoder in flax, TPU-first.

Second LM family beside GPT-2 (models/gpt2.py): the modern pre-norm
decoder recipe — RMSNorm, rotary position embeddings, grouped-query
attention, SwiGLU MLP, no biases, weights untied from the embedding.  The
reference framework ships no model implementations (its LM benchmarks
wrap HuggingFace torch through TorchTrainer, python/ray/train/
huggingface/); this is a ground-up jax design sharing the GPT-2 module's
conventions:

- bfloat16 activations / fp32 params via ``dtype``,
- attention through ray_tpu.ops (Pallas flash on TPU, XLA fallback) after
  GQA head expansion,
- the same parameter-name → logical-axis table as GPT-2, so
  ShardingRules runs it 1-chip, DP, FSDP or DP×TP unchanged.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import mha_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_position_embeddings: int = 2048
    num_layers: int = 8
    num_heads: int = 8
    num_kv_heads: int = 4          # < num_heads → grouped-query attention
    hidden_size: int = 512
    intermediate_size: Optional[int] = None  # default ~8/3 * hidden
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    use_flash: Optional[bool] = None

    @classmethod
    def tiny(cls, **kw):  # test-sized
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_position_embeddings", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("hidden_size", 64)
        return cls(**kw)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def mlp_dim(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        # The 2/3·4h SwiGLU sizing, rounded to a multiple of 32 for MXU
        # tiling.
        raw = int(self.hidden_size * 8 / 3)
        return ((raw + 31) // 32) * 32


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # Variance in fp32 regardless of activation dtype.
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        norm = x * jax.lax.rsqrt(var + self.eps).astype(x.dtype)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return norm * scale.astype(x.dtype)


def rope_tables(length: int, head_dim: int, theta: float):
    """[L, D/2] cos/sin tables."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                        dtype=jnp.float32) / head_dim))
    angles = jnp.arange(length, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs of channels; x: [B, L, H, D]."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos = cos[None, :, None, :].astype(x.dtype)
    sin = sin[None, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        B, L, _ = x.shape
        hd = c.head_dim
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=c.dtype, name=name)
        q = dense(c.num_heads * hd, "q_proj")(x).reshape(
            B, L, c.num_heads, hd)
        k = dense(c.num_kv_heads * hd, "k_proj")(x).reshape(
            B, L, c.num_kv_heads, hd)
        v = dense(c.num_kv_heads * hd, "v_proj")(x).reshape(
            B, L, c.num_kv_heads, hd)
        cos, sin = rope_tables(L, hd, c.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if c.num_kv_heads != c.num_heads:
            # GQA: expand kv heads to query heads (XLA turns the repeat
            # into a broadcast; memory win is in the kv cache/proj, which
            # stays at num_kv_heads).
            rep = c.num_heads // c.num_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        out = mha_attention(q, k, v, causal=True, use_flash=c.use_flash)
        out = out.reshape(B, L, c.num_heads * hd)
        return dense(c.hidden_size, "o_proj")(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=c.dtype, name=name)
        gate = dense(c.mlp_dim, "gate_proj")(x)
        up = dense(c.mlp_dim, "up_proj")(x)
        return dense(c.hidden_size, "down_proj")(nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        x = x + LlamaAttention(c, name="attn")(
            RMSNorm(c.rms_eps, c.dtype, name="attn_norm")(x))
        x = x + LlamaMLP(c, name="mlp")(
            RMSNorm(c.rms_eps, c.dtype, name="mlp_norm")(x))
        return x


class Llama(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array) -> jax.Array:
        c = self.config
        emb = nn.Embed(c.vocab_size, c.hidden_size,
                       dtype=c.dtype, name="embed")
        x = emb(input_ids)
        for i in range(c.num_layers):
            x = LlamaBlock(c, name=f"layer_{i}")(x)
        x = RMSNorm(c.rms_eps, c.dtype, name="final_norm")(x)
        # Untied LM head (llama convention), fp32 logits for the softmax.
        logits = nn.Dense(c.vocab_size, use_bias=False, dtype=jnp.float32,
                          name="lm_head")(x.astype(jnp.float32))
        return logits


def llama_loss_fn(params, apply_fn, batch) -> jax.Array:
    """Next-token cross-entropy (same contract as gpt2_loss_fn)."""
    ids = batch["input_ids"]
    logits = apply_fn({"params": params}, ids)[:, :-1]
    labels = ids[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)
