"""Llama-family decoder in flax, TPU-first.

Second LM family beside GPT-2 (models/gpt2.py): the modern pre-norm
decoder recipe — RMSNorm, rotary position embeddings, grouped-query
attention, SwiGLU MLP, no biases, weights untied from the embedding.  The
reference framework ships no model implementations (its LM benchmarks
wrap HuggingFace torch through TorchTrainer, python/ray/train/
huggingface/); this is a ground-up jax design sharing the GPT-2 module's
conventions:

- bfloat16 activations / fp32 params via ``dtype``,
- attention through ray_tpu.ops (Pallas flash on TPU, XLA fallback) after
  GQA head expansion,
- the same parameter-name → logical-axis table as GPT-2, so
  ShardingRules runs it 1-chip, DP, FSDP or DP×TP unchanged.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import cached_attention, mha_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_position_embeddings: int = 2048
    num_layers: int = 8
    num_heads: int = 8
    num_kv_heads: int = 4          # < num_heads → grouped-query attention
    hidden_size: int = 512
    intermediate_size: Optional[int] = None  # default ~8/3 * hidden
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    use_flash: Optional[bool] = None

    @classmethod
    def tiny(cls, **kw):  # test-sized
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_position_embeddings", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("hidden_size", 64)
        return cls(**kw)

    @classmethod
    def llama_1b(cls, **kw):
        """~1.1B-param GQA config (TinyLlama-1.1B shape: 22 layers,
        2048 hidden, 32 q heads over 4 kv heads, 5632 SwiGLU) — the 3D
        pipeline x SPMD x ZeRO scale target (bench.py bench_llama_3d)."""
        kw.setdefault("vocab_size", 32000)
        kw.setdefault("max_position_embeddings", 2048)
        kw.setdefault("num_layers", 22)
        kw.setdefault("num_heads", 32)
        kw.setdefault("num_kv_heads", 4)
        kw.setdefault("hidden_size", 2048)
        kw.setdefault("intermediate_size", 5632)
        return cls(**kw)

    @classmethod
    def draft_of(cls, target: "LlamaConfig", num_layers: int = 1,
                 num_heads: Optional[int] = None,
                 num_kv_heads: Optional[int] = None,
                 hidden_size: Optional[int] = None, **kw):
        """A speculative-decoding draft config for ``target``: same
        vocab, context length and dtype (the serve engine's hard
        requirements), everything else shrunk — one layer at half width
        by default, GQA ratio preserved."""
        heads = num_heads or max(1, target.num_heads // 2)
        kvh = num_kv_heads or max(
            1, heads * target.num_kv_heads // target.num_heads)
        heads -= heads % kvh  # q heads must group evenly over kv heads
        hidden = hidden_size or max(heads * 8, target.hidden_size // 2)
        hidden -= hidden % heads
        return cls(vocab_size=target.vocab_size,
                   max_position_embeddings=target.max_position_embeddings,
                   num_layers=num_layers, num_heads=heads,
                   num_kv_heads=kvh, hidden_size=hidden,
                   rope_theta=target.rope_theta, dtype=target.dtype, **kw)

    @property
    def block_params(self) -> int:
        """Parameters per decoder block: q/o at h^2, GQA k/v at
        h^2 * kv/heads, three SwiGLU mats at h*mlp (+2 RMSNorm scales)."""
        h, m = self.hidden_size, self.mlp_dim
        kv = self.num_kv_heads / self.num_heads
        return int(h * h * (2 + 2 * kv) + 3 * h * m + 2 * h)

    @property
    def n_params(self) -> int:
        """Total parameter count (embed + blocks + final norm + head)."""
        h = self.hidden_size
        return int(2 * self.vocab_size * h + h
                   + self.num_layers * self.block_params)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def mlp_dim(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        # The 2/3·4h SwiGLU sizing, rounded to a multiple of 32 for MXU
        # tiling.
        raw = int(self.hidden_size * 8 / 3)
        return ((raw + 31) // 32) * 32


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # Variance in fp32 regardless of activation dtype.
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        norm = x * jax.lax.rsqrt(var + self.eps).astype(x.dtype)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return norm * scale.astype(x.dtype)


def rope_tables(length: int, head_dim: int, theta: float):
    """[L, D/2] cos/sin tables."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                        dtype=jnp.float32) / head_dim))
    angles = jnp.arange(length, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs of channels; x: [B, L, H, D].  cos/sin are either
    [L, D/2] (contiguous-from-zero, the training path) or [B, L, D/2]
    (per-token absolute positions, the decode path)."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    cos = cos[:, :, None, :].astype(x.dtype)
    sin = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, kv=None, positions=None):
        """kv = (k_cache, v_cache, lengths) → incremental decode: rope is
        applied at the tokens' absolute ``positions``, the cache stays at
        num_kv_heads (the GQA memory win carries into the KV pages;
        cached_attention expands heads after concat), and the layer also
        returns this step's post-rope (k, v) for the caller's cache."""
        c = self.config
        B, L, _ = x.shape
        hd = c.head_dim
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=c.dtype, name=name)
        q = dense(c.num_heads * hd, "q_proj")(x).reshape(
            B, L, c.num_heads, hd)
        k = dense(c.num_kv_heads * hd, "k_proj")(x).reshape(
            B, L, c.num_kv_heads, hd)
        v = dense(c.num_kv_heads * hd, "v_proj")(x).reshape(
            B, L, c.num_kv_heads, hd)
        if kv is not None:
            cos, sin = rope_tables(c.max_position_embeddings, hd,
                                   c.rope_theta)
            q = apply_rope(q, cos[positions], sin[positions])
            k = apply_rope(k, cos[positions], sin[positions])
            k_cache, v_cache, lengths = kv
            out = cached_attention(q, k, v, k_cache, v_cache, lengths)
            out = out.reshape(B, L, c.num_heads * hd)
            return dense(c.hidden_size, "o_proj")(out), (k, v)
        cos, sin = rope_tables(L, hd, c.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if c.num_kv_heads != c.num_heads:
            # GQA: expand kv heads to query heads (XLA turns the repeat
            # into a broadcast; memory win is in the kv cache/proj, which
            # stays at num_kv_heads).
            rep = c.num_heads // c.num_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        out = mha_attention(q, k, v, causal=True, use_flash=c.use_flash)
        out = out.reshape(B, L, c.num_heads * hd)
        return dense(c.hidden_size, "o_proj")(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=c.dtype, name=name)
        gate = dense(c.mlp_dim, "gate_proj")(x)
        up = dense(c.mlp_dim, "up_proj")(x)
        return dense(c.hidden_size, "down_proj")(nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, kv=None, positions=None):
        c = self.config
        if kv is not None:
            attn, new_kv = LlamaAttention(c, name="attn")(
                RMSNorm(c.rms_eps, c.dtype, name="attn_norm")(x),
                kv=kv, positions=positions)
            x = x + attn
            x = x + LlamaMLP(c, name="mlp")(
                RMSNorm(c.rms_eps, c.dtype, name="mlp_norm")(x))
            return x, new_kv
        x = x + LlamaAttention(c, name="attn")(
            RMSNorm(c.rms_eps, c.dtype, name="attn_norm")(x))
        x = x + LlamaMLP(c, name="mlp")(
            RMSNorm(c.rms_eps, c.dtype, name="mlp_norm")(x))
        return x


class Llama(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array, positions: jax.Array = None,
                 kv_caches=None, kv_lengths: jax.Array = None):
        """Full-context: input_ids [B, L] → logits [B, L, vocab].  With
        ``kv_caches`` (per-layer (k, v) at num_kv_heads, valid rows per
        ``kv_lengths``) and absolute ``positions``: incremental decode,
        returning (logits, new_kvs) — the same contract as GPT2."""
        c = self.config
        emb = nn.Embed(c.vocab_size, c.hidden_size,
                       dtype=c.dtype, name="embed")
        x = emb(input_ids)
        decode = kv_caches is not None
        new_kvs = []
        for i in range(c.num_layers):
            if decode:
                x, nkv = LlamaBlock(c, name=f"layer_{i}")(
                    x, kv=(kv_caches[i][0], kv_caches[i][1], kv_lengths),
                    positions=positions)
                new_kvs.append(nkv)
            else:
                x = LlamaBlock(c, name=f"layer_{i}")(x)
        x = RMSNorm(c.rms_eps, c.dtype, name="final_norm")(x)
        # Untied LM head (llama convention), fp32 logits for the softmax.
        logits = nn.Dense(c.vocab_size, use_bias=False, dtype=jnp.float32,
                          name="lm_head")(x.astype(jnp.float32))
        if decode:
            return logits, new_kvs
        return logits


class LlamaStage(nn.Module):
    """One pipeline chunk of a split Llama (see :func:`split_stages`).

    Chunk 0 owns the token embedding and consumes ids; middle chunks
    consume/produce hidden states; the last chunk owns the final RMSNorm
    and the (already-untied, llama convention) LM head and produces the
    loss-side logits.  Rope is positional-from-zero inside each block,
    so splitting changes nothing about the attention math."""

    config: LlamaConfig
    first: bool
    last: bool
    blocks: tuple  # (start, stop) block index range owned by this chunk

    @nn.compact
    def __call__(self, x):
        c = self.config
        if self.first:
            x = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                         name="embed")(x)
        else:
            x = x.astype(c.dtype)
        for i in range(*self.blocks):
            x = LlamaBlock(c, name=f"layer_{i}")(x)
        if self.last:
            x = RMSNorm(c.rms_eps, c.dtype, name="final_norm")(x)
            logits = nn.Dense(c.vocab_size, use_bias=False,
                              dtype=jnp.float32, name="lm_head")(
                x.astype(jnp.float32))
            return logits
        return x


def _stage_ce_loss(logits: jax.Array, ids: jax.Array) -> jax.Array:
    """Next-token CE on a microbatch (same objective as llama_loss_fn)."""
    logits = logits[:, :-1]
    labels = ids[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def llama_head_cost(config: LlamaConfig) -> float:
    """LM-head cost in llama block-equivalents — the GQA/SwiGLU-aware
    analogue of gpt2's ``vocab/(12*hidden)``: a llama block costs
    ``h^2*(2 + 2*kv/heads) + 3*h*mlp`` param-FLOP units, the head
    ``vocab*h``."""
    return (config.vocab_size * config.hidden_size) / config.block_params


def split_stages(config: LlamaConfig, num_stages: int, *,
                 virtual_per_rank: int = 1,
                 boundary_dtype: Any = jnp.float32, seed: int = 0):
    """Split a Llama config into ``num_stages * virtual_per_rank``
    pipeline chunks for
    :class:`ray_tpu.parallel.mpmd_pipeline.MPMDPipeline` — same contract
    as ``models/gpt2.py::split_stages`` (GLOBAL chunk order, last chunk
    is the loss fn, init fns run on the stage actors), with the block
    cost model adjusted for GQA attention + SwiGLU MLP
    (:func:`llama_head_cost`).  Embedding pins to chunk 0 (stage 0),
    head to the last chunk (last stage), interleaved assignment
    ``chunk c -> stage c % num_stages``."""
    from ray_tpu.models.pipeline_split import balance_chunks, chunk_flags

    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    C = num_stages * max(1, int(virtual_per_rank))
    bounds = balance_chunks(config.num_layers, C, embed_cost=0.3,
                            head_cost=llama_head_cost(config))

    stage_fns, init_fns = [], []
    for k, (first, last) in enumerate(chunk_flags(C)):
        module = LlamaStage(config, first=first, last=last,
                            blocks=bounds[k])

        if last:
            def fn(params, x, target, _m=module):
                logits = _m.apply({"params": params}, x)
                return _stage_ce_loss(logits, target)
        else:
            def fn(params, x, _m=module, _bd=boundary_dtype):
                return _m.apply({"params": params}, x).astype(_bd)

        def init_fn(_m=module, _first=first, _seed=seed + k, _c=config):
            dummy = jnp.zeros((1, 8), jnp.int32) if _first else \
                jnp.zeros((1, 8, _c.hidden_size), _c.dtype)
            return _m.init(jax.random.PRNGKey(_seed), dummy)["params"]

        stage_fns.append(fn)
        init_fns.append(init_fn)
    return stage_fns, init_fns


def llama_loss_fn(params, apply_fn, batch) -> jax.Array:
    """Next-token cross-entropy (same contract as gpt2_loss_fn)."""
    ids = batch["input_ids"]
    logits = apply_fn({"params": params}, ids)[:, :-1]
    labels = ids[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)
