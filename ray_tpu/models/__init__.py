"""Model zoo (flax): GPT-2 + Llama LM families, ResNets, MLP, NatureCNN.

The reference's model layer is RLlib's ModelCatalog + torch/tf ModelV2
(rllib/models/catalog.py, rllib/models/torch/*) plus whatever user code
brings to Train.  Here models are flax modules designed for pjit: static
shapes, bfloat16-friendly, logical sharding annotations exposed per model
via `param_logical_axes`.
"""
from ray_tpu.models.gpt2 import (  # noqa: F401
    GPT2,
    GPT2Config,
    GPT2Stage,
    GPT2WithValue,
    gpt2_loss_fn,
    split_stages,
)
from ray_tpu.models.llama import (  # noqa: F401
    Llama,
    LlamaConfig,
    LlamaStage,
    llama_loss_fn,
)
from ray_tpu.models.resnet import ResNet, ResNetConfig  # noqa: F401
from ray_tpu.models.mlp import MLP  # noqa: F401
from ray_tpu.models.nature_cnn import NatureCNN  # noqa: F401
