"""ResNet family in flax (ResNet-18/50 + CIFAR stem variant).

For the Train north-star "ResNet-50/CIFAR-10 DataParallel" config
(BASELINE.json; reference benchmark: doc/source/ray-air/benchmarks.rst
TorchTrainer ResNet).  NHWC layout (TPU-native), bfloat16 compute, fp32
batch-norm statistics.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # resnet50
    num_classes: int = 10
    num_filters: int = 64
    bottleneck: bool = True
    cifar_stem: bool = False  # 3x3 stem, no maxpool (32x32 inputs)
    dtype: Any = jnp.bfloat16

    @classmethod
    def resnet18(cls, **kw):
        return cls(stage_sizes=(2, 2, 2, 2), bottleneck=False, **kw)

    @classmethod
    def resnet50(cls, **kw):
        return cls(**kw)

    @classmethod
    def resnet50_cifar(cls, **kw):
        return cls(cifar_stem=True, **kw)

    @classmethod
    def tiny(cls, **kw):
        return cls(stage_sizes=(1, 1), bottleneck=False, num_filters=8,
                   cifar_stem=True, **kw)


class ResNetBlock(nn.Module):
    filters: int
    strides: int
    bottleneck: bool
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=jnp.float32)
        residual = x
        if self.bottleneck:
            y = conv(self.filters, (1, 1))(x)
            y = nn.relu(norm()(y))
            y = conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
            y = nn.relu(norm()(y))
            y = conv(4 * self.filters, (1, 1))(y)
            y = norm(scale_init=nn.initializers.zeros)(y)
            out_filters = 4 * self.filters
        else:
            y = conv(self.filters, (3, 3), strides=(self.strides,) * 2)(x)
            y = nn.relu(norm()(y))
            y = conv(self.filters, (3, 3))(y)
            y = norm(scale_init=nn.initializers.zeros)(y)
            out_filters = self.filters
        if residual.shape != y.shape:
            residual = conv(out_filters, (1, 1),
                            strides=(self.strides,) * 2)(residual)
            residual = norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        c = self.config
        x = x.astype(c.dtype)
        if c.cifar_stem:
            x = nn.Conv(c.num_filters, (3, 3), use_bias=False,
                        dtype=c.dtype, name="stem")(x)
        else:
            x = nn.Conv(c.num_filters, (7, 7), strides=(2, 2),
                        use_bias=False, dtype=c.dtype, name="stem")(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 dtype=jnp.float32, name="stem_bn")(x))
        if not c.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(c.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = ResNetBlock(c.num_filters * 2 ** i, strides,
                                c.bottleneck, c.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(c.num_classes, dtype=jnp.float32, name="head")(x)


def resnet_loss_fn(params, batch_stats, apply_fn, batch):
    """Softmax CE with batch-norm stat updates.
    batch: {"image": [B,H,W,C], "label": [B]}."""
    logits, new_state = apply_fn(
        {"params": params, "batch_stats": batch_stats}, batch["image"],
        train=True, mutable=["batch_stats"])
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, batch["label"][:, None], axis=-1)[:, 0]
    acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
    return -jnp.mean(ll), (new_state["batch_stats"], acc)
