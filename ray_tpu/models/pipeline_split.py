"""Shared cost-balanced pipeline-stage splitting for the LM families.

Both ``models/gpt2.py`` and ``models/llama.py`` split their decoder into
``num_stages * virtual_per_rank`` chunks for the MPMD pipeline
(``parallel/mpmd_pipeline.py``): blocks are partitioned by COST, not
count — the embedding lookup is nearly free but the LM-head matmul costs
``vocab_params / block_params`` block-equivalents (5+ blocks at small
widths), so the head-owning chunk gets proportionally fewer blocks.  The
embedding is pinned to chunk 0 and the head to the last chunk; with
interleaving (``virtual_per_rank > 1``) chunk c is owned by physical
stage ``c % num_stages``, which puts the embedding on stage 0 and the
head on the last stage — the Megatron assignment.
"""
from __future__ import annotations

from typing import List, Tuple


def balance_chunks(num_blocks: int, num_chunks: int, *,
                   embed_cost: float, head_cost: float
                   ) -> List[Tuple[int, int]]:
    """Partition ``num_blocks`` transformer blocks into ``num_chunks``
    contiguous ``(start, stop)`` ranges balanced by cumulative cost.

    Chunk 0 additionally carries ``embed_cost`` and the last chunk
    ``head_cost`` (in block-equivalents).  Every middle chunk owns at
    least one block; the first and last chunks may be block-free (an
    embedding-only or head-only chunk — how a tiny model still splits
    into ``S * v`` chunks), so up to ``num_blocks + 2`` chunks fit."""
    if num_chunks < 1:
        raise ValueError(f"num_chunks must be >= 1, got {num_chunks}")
    if num_chunks > num_blocks + 2:
        raise ValueError(
            f"cannot split {num_blocks} blocks into {num_chunks} chunks "
            "(middles need >= 1 block; only the embed/head chunks may be "
            "block-free)")
    per = (embed_cost + num_blocks + head_cost) / num_chunks
    bounds: List[Tuple[int, int]] = []
    start, cum = 0, embed_cost
    for c in range(num_chunks - 1):
        target = (c + 1) * per
        stop = start
        # Leave >= 1 block for every LATER middle chunk (indices
        # c+1 .. num_chunks-2).
        later_middles = max(0, num_chunks - 2 - c)
        max_stop = num_blocks - later_middles
        while stop < max_stop and cum + 1.0 <= target + 0.5:
            stop += 1
            cum += 1.0
        if stop == start and 0 < c and start < max_stop:
            stop, cum = start + 1, cum + 1.0  # middles own >= 1 block
        bounds.append((start, stop))
        start = stop
    bounds.append((start, num_blocks))
    return bounds


def chunk_flags(num_chunks: int):
    """``(first, last)`` flag pairs per chunk index."""
    return [(c == 0, c == num_chunks - 1) for c in range(num_chunks)]
