"""Distributed Dataset on object-store blocks.

Reference: python/ray/data/dataset.py:156 (Dataset), _internal/plan.py
(lazy ExecutionPlan).  Since the flow substrate landed, the per-block
transforms (``map_batches``/``map``/``filter``) are LAZY plan ops
(data/execution.py): nothing runs at call time, and the consuming
iterators (``iter_batches``/``iter_device_batches``/``count``/``take``)
drive the plan per-block through a bounded
:class:`ray_tpu.parallel.flow.RefStream` — read→map→consume overlap with
peak resident blocks capped by the window, byte-identical to the old
eager engine (same per-block kernels, same order).  Whole-dataset
operators (repartition/sort/split/zip/writes) still materialize the plan
eagerly first — they are barriers by nature.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data import execution
from ray_tpu.data.block import (
    apply_batch_fn,
    block_from_items,
    block_from_numpy,
    block_num_rows,
    block_to_numpy,
    concat_blocks,
)


@ray_tpu.remote
def _map_block(blk, fn, batch_format):
    return apply_batch_fn(blk, fn, batch_format)


@ray_tpu.remote
def _filter_block(blk, fn):
    return execution.apply_op(blk, ("filter", fn, None))


@ray_tpu.remote
def _count_block(blk):
    return blk.num_rows


@ray_tpu.remote
def _write_block(blk, path: str, fmt: str) -> str:
    import json as json_mod
    import os

    # Task-side: the writing node may not be the driver's host.
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(blk, path)
    elif fmt == "csv":
        import pyarrow.csv as pcsv

        pcsv.write_csv(blk, path)
    elif fmt == "json":
        with open(path, "w") as f:
            for row in blk.to_pylist():
                f.write(json_mod.dumps(row) + "\n")
    else:
        raise ValueError(f"bad write format {fmt!r}")
    return path


@ray_tpu.remote
def _concat(*blks):
    return concat_blocks(list(blks))


@ray_tpu.remote
def _slice_block(blk, start, end):
    return block_mod.block_slice(blk, start, end)


@ray_tpu.remote
def _concat_slices(ranges, *blks):
    """Concatenate [start, end) slices of the given blocks (the
    repartition reduce side: one output block's pieces only)."""
    parts = [block_mod.block_slice(b, s, e)
             for b, (s, e) in zip(blks, ranges)]
    return concat_blocks(parts) if parts else block_mod.block_from_items([])


@ray_tpu.remote
def _read_file(reader, path: str, columns=None):
    # `reader` is resolved driver-side and ships with the task — worker
    # processes never see driver-local register_datasource() calls.
    return reader(path, columns)


class Dataset:
    """``sources`` holds object refs to materialized blocks and/or lazy
    ``("read", reader, path, columns)`` descriptors; ``plan`` holds the
    per-block ops not yet applied.  ``_blocks`` (the pre-substrate
    internal contract, still used by grouped/sort) materializes the plan
    eagerly and caches the resulting refs."""

    def __init__(self, block_refs: List[Any],
                 plan: Optional[List[execution.PlanOp]] = None):
        self._sources: List[Any] = list(block_refs)
        self._plan: List[execution.PlanOp] = list(plan or [])

    @property
    def _blocks(self) -> List[Any]:
        """Materialized block refs: collapses lazy reads + pending plan
        ops into store-resident blocks (the old eager engine's state)."""
        if self._plan or any(execution.is_read_source(s)
                             for s in self._sources):
            self._sources = execution.PlanExecutor(
                self._sources, self._plan).materialize_refs()
            self._plan = []
        return self._sources

    def _executor(self, window: Optional[int] = None,
                  name: str = "dataset") -> execution.PlanExecutor:
        return execution.PlanExecutor(self._sources, self._plan,
                                      window=window, name=name)

    # ---------------- creation ----------------
    @staticmethod
    def from_items(items: List[Any], parallelism: int = 8) -> "Dataset":
        chunks = np.array_split(np.arange(len(items)), max(1, min(parallelism, len(items))))
        # Block puts ride put_many: one coalesced control-plane message
        # for the whole set of blocks instead of one per block.
        return Dataset(ray_tpu.put_many(
            [block_from_items([items[i] for i in c])
             for c in chunks if len(c)]))

    @staticmethod
    def range(n: int, parallelism: int = 8) -> "Dataset":
        bounds = np.linspace(0, n, max(1, parallelism) + 1, dtype=int)
        return Dataset(ray_tpu.put_many(
            [block_from_numpy({"id": np.arange(a, b)})
             for a, b in zip(bounds, bounds[1:]) if b > a]))

    @staticmethod
    def from_numpy(arrays: Dict[str, np.ndarray], parallelism: int = 8
                   ) -> "Dataset":
        n = len(next(iter(arrays.values())))
        bounds = np.linspace(0, n, max(1, parallelism) + 1, dtype=int)
        blocks = [block_from_numpy({k: v[a:b] for k, v in arrays.items()})
                  for a, b in zip(bounds, bounds[1:]) if b > a]
        return Dataset(ray_tpu.put_many(blocks))

    @staticmethod
    def read(paths: Union[str, List[str]], fmt: str,
             columns=None) -> "Dataset":
        from ray_tpu.data.datasource import expand_paths, resolve_datasource

        reader = resolve_datasource(fmt)
        # Lazy read sources: no task runs until a consumer drives the
        # plan, and then the read fuses with the chained per-block ops.
        return Dataset([("read", reader, p, columns)
                        for p in expand_paths(paths)])

    # ---------------- transforms (lazy plan ops) ----------------
    def _with_op(self, op: execution.PlanOp) -> "Dataset":
        return Dataset(self._sources, self._plan + [op])

    def map_batches(self, fn: Callable, batch_format: str = "numpy"
                    ) -> "Dataset":
        return self._with_op(("map_batches", fn, batch_format))

    def map(self, fn: Callable) -> "Dataset":
        def row_fn(batch: dict):
            rows = _batch_to_rows(batch)
            out = [fn(r) for r in rows]
            return _rows_to_batch(out)

        return self.map_batches(row_fn, batch_format="numpy")

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_op(("filter", fn, None))

    def repartition(self, num_blocks: int) -> "Dataset":
        """Block-parallel repartition via a slice plan: each output block
        concatenates only the input slices it needs — no task ever holds
        the whole dataset (the previous global-concat form bounded the
        dataset by one worker's memory)."""
        blocks = self._blocks  # barrier: slice plan needs all lengths
        lengths = ray_tpu.get([_count_block.remote(b) for b in blocks])
        total = int(sum(lengths))
        starts = np.cumsum([0] + lengths)  # input block i covers
        bounds = np.linspace(0, total, num_blocks + 1, dtype=int)
        out = []
        for a, b in zip(bounds, bounds[1:]):
            pieces = []
            for i, (s, ln) in enumerate(zip(starts, lengths)):
                lo, hi = max(a, s), min(b, s + ln)
                if hi > lo:
                    pieces.append((blocks[i], int(lo - s), int(hi - s)))
            if pieces:
                out.append(_concat_slices.remote(
                    [p[1:] for p in pieces], *[p[0] for p in pieces]))
            else:
                # More output blocks than rows: an empty output must keep
                # the dataset's SCHEMA (a 0-row slice of a real block), or
                # schema()/iter_batches break on the placeholder type.
                out.append(_slice_block.remote(blocks[0], 0, 0))
        return Dataset(out)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Block-local shuffle after a round-robin repartition (cheap
        global mix; the streaming executor's push shuffle is the full-
        radius form).  Per-block permutations are DECORRELATED: every
        block derives its rng from ``(seed, block_index)`` through a
        SeedSequence spawn — the old engine fed every block the identical
        seed, so all blocks were permuted the same way — and
        ``seed=None`` draws fresh OS entropy per call (irreproducible by
        request, not by accident)."""
        entropy = np.random.SeedSequence(seed).entropy

        def shuf(batch: dict, block_index: int):
            rng = np.random.default_rng(np.random.SeedSequence(
                entropy=entropy, spawn_key=(int(block_index),)))
            n = len(next(iter(batch.values()))) if batch else 0
            idx = rng.permutation(n)
            return {k: v[idx] for k, v in batch.items()}

        out = self.repartition(self.num_blocks())
        return out._with_op(("map_batches_indexed", shuf, "numpy"))

    def split(self, n: int, equal: bool = True) -> List["Dataset"]:
        """Per-worker shards (reference: Dataset.split with locality hints →
        train ingest, dataset_spec.py:46-99)."""
        total = self.count()
        per = total // n
        whole = _concat.remote(*self._blocks)
        out = []
        for i in range(n):
            start = i * per
            end = (i + 1) * per if (equal or i < n - 1) else total
            out.append(Dataset([_slice_block.remote(whole, start, end)]))
        return out

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed sample-sort (reference: Dataset.sort →
        _internal/sort.py two-phase range partition)."""
        from ray_tpu.data.grouped import sort_impl

        return Dataset(sort_impl(self._blocks, key, descending))

    def groupby(self, key: str, num_partitions: Optional[int] = None):
        """Hash-shuffle groupby (reference: Dataset.groupby →
        grouped_data.py)."""
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key, num_partitions)

    def zip(self, other: "Dataset") -> "Dataset":
        a = concat_blocks(ray_tpu.get(self._blocks))
        b = concat_blocks(ray_tpu.get(other._blocks))
        import pyarrow as pa

        cols = {**{n: a.column(n) for n in a.column_names},
                **{n: b.column(n) for n in b.column_names}}
        return Dataset([ray_tpu.put(pa.table(cols))])

    # ---------------- consumption (drives the plan, windowed) -----------
    def count(self, window: Optional[int] = None) -> int:
        total = 0
        for ref in self._executor(window, name="count").iter_count_refs():
            total += int(ray_tpu.get(ref))
            del ref
        return total

    def take(self, n: int = 20, window: Optional[int] = None) -> List[dict]:
        out: List[dict] = []
        refs = self._executor(window, name="take").iter_block_refs()
        try:
            for ref in refs:
                blk = ray_tpu.get(ref)
                del ref
                out.extend(blk.to_pylist()[: n - len(out)])
                if len(out) >= n:
                    break
        finally:
            refs.close()  # early exit: release the in-flight window
        return out

    def take_all(self, window: Optional[int] = None) -> List[dict]:
        return list(self.iter_rows(window=window))

    def schema(self):
        # Only the first block is executed (plan ops preserve schema
        # presence even on 0-row outputs).
        ex = execution.PlanExecutor(self._sources[:1], self._plan,
                                    window=1, name="schema")
        for ref in ex.iter_block_refs():
            return ray_tpu.get(ref).schema
        raise ValueError("schema() on an empty dataset")

    def num_blocks(self) -> int:
        return len(self._sources)

    def iter_rows(self, window: Optional[int] = None) -> Iterator[dict]:
        for ref in self._executor(window, name="rows").iter_block_refs():
            blk = ray_tpu.get(ref)
            del ref
            yield from blk.to_pylist()

    def iter_batches(self, batch_size: int = 256, batch_format: str = "numpy",
                     drop_last: bool = False,
                     window: Optional[int] = None) -> Iterator[Batch]:
        """Stream batches; the plan executes per-block with at most
        ``window`` blocks in flight (read→map→consume overlap)."""
        carry: Optional[dict] = None
        for ref in self._executor(window, name="batches").iter_block_refs():
            blk = ray_tpu.get(ref)
            del ref  # release the store copy once rows are in-process
            batch = block_to_numpy(blk)
            del blk
            if carry is not None:
                batch = {k: np.concatenate([carry[k], batch[k]])
                         for k in batch}
            n = len(next(iter(batch.values()))) if batch else 0
            pos = 0
            while n - pos >= batch_size:
                yield _format({k: v[pos:pos + batch_size]
                               for k, v in batch.items()}, batch_format)
                pos += batch_size
            carry = {k: v[pos:] for k, v in batch.items()} if pos < n else None
        if carry is not None and not drop_last and \
                len(next(iter(carry.values()))) > 0:
            yield _format(carry, batch_format)

    def iter_device_batches(self, batch_size: int = 256, sharding=None,
                            prefetch: int = 2,
                            window: Optional[int] = None) -> Iterator[Any]:
        """ML-ingest hot path: host batches → jax.device_put (optionally
        sharded over a mesh) on a BACKGROUND thread feeding a bounded
        queue, so the store fetch + H2D transfer overlap the consumer's
        step (reference analogue: iter_torch_batches + pin_memory/
        prefetch worker, data/dataset_iterator.py).  prefetch=0 keeps the
        old inline path; see ray_tpu.data.prefetch.DevicePrefetcher."""
        from ray_tpu.data.prefetch import DevicePrefetcher

        return DevicePrefetcher(
            self.iter_batches(batch_size, "numpy", window=window),
            sharding=sharding, prefetch=prefetch)

    def materialize(self) -> "Dataset":
        blocks = self._blocks  # collapse lazy reads + pending plan ops
        ray_tpu.wait(blocks, num_returns=len(blocks))
        return self

    def streaming(self, store_budget: Optional[int] = None,
                  max_inflight_blocks: Optional[int] = None):
        """Switch to the bounded-memory streaming executor
        (ray_tpu.data.streaming.StreamingDataset).  The pending plan
        carries over verbatim — ops are the same tuples both engines
        execute."""
        from ray_tpu.data.streaming import StreamingDataset

        sources = [s if execution.is_read_source(s) else (lambda r=s: r)
                   for s in self._sources]
        return StreamingDataset(sources, stages=list(self._plan),
                                store_budget=store_budget,
                                max_inflight_blocks=max_inflight_blocks)

    # ---------------- writes (reference: Dataset.write_parquet/csv/json,
    # python/ray/data/dataset.py + file_datasink.py: one file per block,
    # written by the task that holds the block) ----------------
    def _write(self, path: str, fmt: str, ext: str, mode: str) -> List[str]:
        import glob as glob_mod
        import os

        existing = glob_mod.glob(os.path.join(path, f"part-*.{ext}"))
        if existing:
            if mode == "overwrite":
                for p in existing:
                    os.remove(p)  # a shorter write must not leave a stale
                    # tail that doubles rows on read-back
            else:
                raise FileExistsError(
                    f"{path} already holds {len(existing)} part files; "
                    "pass mode='overwrite' to replace them")
        refs = [
            _write_block.remote(
                b, os.path.join(path, f"part-{i:05d}.{ext}"), fmt)
            for i, b in enumerate(self._blocks)
        ]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str, mode: str = "error") -> List[str]:
        return self._write(path, "parquet", "parquet", mode)

    def write_csv(self, path: str, mode: str = "error") -> List[str]:
        return self._write(path, "csv", "csv", mode)

    def write_json(self, path: str, mode: str = "error") -> List[str]:
        return self._write(path, "json", "json", mode)

    def stats(self) -> dict:
        return {"num_blocks": len(self._sources), "count": self.count()}


Batch = Union[Dict[str, np.ndarray], Any]


def _format(batch: Dict[str, np.ndarray], batch_format: str):
    if batch_format == "numpy":
        return batch
    if batch_format == "pandas":
        import pandas as pd

        return pd.DataFrame(batch)
    if batch_format == "pyarrow":
        return block_from_numpy(batch)
    raise ValueError(batch_format)


def _batch_to_rows(batch: Dict[str, np.ndarray]) -> List[dict]:
    keys = list(batch)
    n = len(batch[keys[0]]) if keys else 0
    return [{k: batch[k][i] for k in keys} for i in range(n)]


def _rows_to_batch(rows: List[Any]) -> Dict[str, np.ndarray]:
    if rows and isinstance(rows[0], dict):
        return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
    return {"item": np.asarray(rows)}
