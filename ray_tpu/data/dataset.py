"""Distributed Dataset on object-store blocks.

Reference: python/ray/data/dataset.py:156 (Dataset), _internal/plan.py
(lazy ExecutionPlan).  Round-1 engine is eager block-parallel (the
reference's original bulk executor): every transform fans out one remote
task per block and yields a new Dataset of result refs.  The streaming
executor with backpressure (reference streaming_executor.py:31) is the
round-2 upgrade; the ML-ingest path — read → map_batches → split →
iter_batches with device prefetch — is complete here.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import (
    apply_batch_fn,
    block_from_items,
    block_from_numpy,
    block_num_rows,
    block_to_numpy,
    concat_blocks,
)


@ray_tpu.remote
def _map_block(blk, fn, batch_format):
    return apply_batch_fn(blk, fn, batch_format)


@ray_tpu.remote
def _filter_block(blk, fn):
    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(fn, pc.Expression):
        # Vectorized fast path: the predicate compiles to arrow compute
        # kernels, no Python per row (reference: Dataset.filter(expr=...)).
        return blk.filter(fn)
    # Row UDF: evaluate over zipped column values — same contract, but no
    # to_pylist() dict materialization per row.
    cols = {name: blk.column(name).to_pylist() for name in blk.column_names}
    names = list(cols)
    mask = [bool(fn(dict(zip(names, vals))))
            for vals in zip(*cols.values())] if names else []
    return blk.filter(pa.array(mask, type=pa.bool_()))


@ray_tpu.remote
def _count_block(blk):
    return blk.num_rows


@ray_tpu.remote
def _write_block(blk, path: str, fmt: str) -> str:
    import json as json_mod
    import os

    # Task-side: the writing node may not be the driver's host.
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(blk, path)
    elif fmt == "csv":
        import pyarrow.csv as pcsv

        pcsv.write_csv(blk, path)
    elif fmt == "json":
        with open(path, "w") as f:
            for row in blk.to_pylist():
                f.write(json_mod.dumps(row) + "\n")
    else:
        raise ValueError(f"bad write format {fmt!r}")
    return path


@ray_tpu.remote
def _concat(*blks):
    return concat_blocks(list(blks))


@ray_tpu.remote
def _slice_block(blk, start, end):
    return block_mod.block_slice(blk, start, end)


@ray_tpu.remote
def _concat_slices(ranges, *blks):
    """Concatenate [start, end) slices of the given blocks (the
    repartition reduce side: one output block's pieces only)."""
    parts = [block_mod.block_slice(b, s, e)
             for b, (s, e) in zip(blks, ranges)]
    return concat_blocks(parts) if parts else block_mod.block_from_items([])


@ray_tpu.remote
def _read_file(reader, path: str, columns=None):
    # `reader` is resolved driver-side and ships with the task — worker
    # processes never see driver-local register_datasource() calls.
    return reader(path, columns)


class Dataset:
    def __init__(self, block_refs: List[Any]):
        self._blocks = block_refs

    # ---------------- creation ----------------
    @staticmethod
    def from_items(items: List[Any], parallelism: int = 8) -> "Dataset":
        chunks = np.array_split(np.arange(len(items)), max(1, min(parallelism, len(items))))
        # Block puts ride put_many: one coalesced control-plane message
        # for the whole set of blocks instead of one per block.
        return Dataset(ray_tpu.put_many(
            [block_from_items([items[i] for i in c])
             for c in chunks if len(c)]))

    @staticmethod
    def range(n: int, parallelism: int = 8) -> "Dataset":
        bounds = np.linspace(0, n, max(1, parallelism) + 1, dtype=int)
        return Dataset(ray_tpu.put_many(
            [block_from_numpy({"id": np.arange(a, b)})
             for a, b in zip(bounds, bounds[1:]) if b > a]))

    @staticmethod
    def from_numpy(arrays: Dict[str, np.ndarray], parallelism: int = 8
                   ) -> "Dataset":
        n = len(next(iter(arrays.values())))
        bounds = np.linspace(0, n, max(1, parallelism) + 1, dtype=int)
        blocks = [block_from_numpy({k: v[a:b] for k, v in arrays.items()})
                  for a, b in zip(bounds, bounds[1:]) if b > a]
        return Dataset(ray_tpu.put_many(blocks))

    @staticmethod
    def read(paths: Union[str, List[str]], fmt: str,
             columns=None) -> "Dataset":
        from ray_tpu.data.datasource import expand_paths, resolve_datasource

        reader = resolve_datasource(fmt)
        return Dataset([_read_file.remote(reader, p, columns)
                        for p in expand_paths(paths)])

    # ---------------- transforms ----------------
    def map_batches(self, fn: Callable, batch_format: str = "numpy"
                    ) -> "Dataset":
        return Dataset([_map_block.remote(b, fn, batch_format)
                        for b in self._blocks])

    def map(self, fn: Callable) -> "Dataset":
        def row_fn(batch: dict):
            rows = _batch_to_rows(batch)
            out = [fn(r) for r in rows]
            return _rows_to_batch(out)

        return self.map_batches(row_fn, batch_format="numpy")

    def filter(self, fn: Callable) -> "Dataset":
        return Dataset([_filter_block.remote(b, fn) for b in self._blocks])

    def repartition(self, num_blocks: int) -> "Dataset":
        """Block-parallel repartition via a slice plan: each output block
        concatenates only the input slices it needs — no task ever holds
        the whole dataset (the previous global-concat form bounded the
        dataset by one worker's memory)."""
        lengths = ray_tpu.get([_count_block.remote(b)
                               for b in self._blocks])
        total = int(sum(lengths))
        starts = np.cumsum([0] + lengths)  # input block i covers
        bounds = np.linspace(0, total, num_blocks + 1, dtype=int)
        out = []
        for a, b in zip(bounds, bounds[1:]):
            pieces = []
            for i, (s, ln) in enumerate(zip(starts, lengths)):
                lo, hi = max(a, s), min(b, s + ln)
                if hi > lo:
                    pieces.append((self._blocks[i], int(lo - s),
                                   int(hi - s)))
            if pieces:
                out.append(_concat_slices.remote(
                    [p[1:] for p in pieces], *[p[0] for p in pieces]))
            else:
                # More output blocks than rows: an empty output must keep
                # the dataset's SCHEMA (a 0-row slice of a real block), or
                # schema()/iter_batches break on the placeholder type.
                out.append(_slice_block.remote(self._blocks[0], 0, 0))
        return Dataset(out)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        def shuf(batch: dict):
            n = len(next(iter(batch.values())))
            idx = np.random.default_rng(seed).permutation(n)
            return {k: v[idx] for k, v in batch.items()}

        # Block-local shuffle after a round-robin repartition (cheap global
        # mix; full push-based shuffle is the round-2 engine's job).
        return self.repartition(len(self._blocks)).map_batches(shuf)

    def split(self, n: int, equal: bool = True) -> List["Dataset"]:
        """Per-worker shards (reference: Dataset.split with locality hints →
        train ingest, dataset_spec.py:46-99)."""
        total = self.count()
        per = total // n
        whole = _concat.remote(*self._blocks)
        out = []
        for i in range(n):
            start = i * per
            end = (i + 1) * per if (equal or i < n - 1) else total
            out.append(Dataset([_slice_block.remote(whole, start, end)]))
        return out

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed sample-sort (reference: Dataset.sort →
        _internal/sort.py two-phase range partition)."""
        from ray_tpu.data.grouped import sort_impl

        return Dataset(sort_impl(self._blocks, key, descending))

    def groupby(self, key: str, num_partitions: Optional[int] = None):
        """Hash-shuffle groupby (reference: Dataset.groupby →
        grouped_data.py)."""
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key, num_partitions)

    def zip(self, other: "Dataset") -> "Dataset":
        a = concat_blocks(ray_tpu.get(self._blocks))
        b = concat_blocks(ray_tpu.get(other._blocks))
        import pyarrow as pa

        cols = {**{n: a.column(n) for n in a.column_names},
                **{n: b.column(n) for n in b.column_names}}
        return Dataset([ray_tpu.put(pa.table(cols))])

    # ---------------- consumption ----------------
    def count(self) -> int:
        return sum(ray_tpu.get([_count_block.remote(b) for b in self._blocks]))

    def take(self, n: int = 20) -> List[dict]:
        out: List[dict] = []
        for b in self._blocks:
            blk = ray_tpu.get(b)
            out.extend(blk.to_pylist()[: n - len(out)])
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[dict]:
        return [r for b in ray_tpu.get(self._blocks) for r in b.to_pylist()]

    def schema(self):
        return ray_tpu.get(self._blocks[0]).schema

    def num_blocks(self) -> int:
        return len(self._blocks)

    def iter_rows(self) -> Iterator[dict]:
        for b in self._blocks:
            yield from ray_tpu.get(b).to_pylist()

    def iter_batches(self, batch_size: int = 256, batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Batch]:
        """Stream batches; blocks are fetched one ahead (prefetch)."""
        carry: Optional[dict] = None
        for b in self._blocks:
            blk = ray_tpu.get(b)
            batch = block_to_numpy(blk)
            if carry is not None:
                batch = {k: np.concatenate([carry[k], batch[k]])
                         for k in batch}
            n = len(next(iter(batch.values()))) if batch else 0
            pos = 0
            while n - pos >= batch_size:
                yield _format({k: v[pos:pos + batch_size]
                               for k, v in batch.items()}, batch_format)
                pos += batch_size
            carry = {k: v[pos:] for k, v in batch.items()} if pos < n else None
        if carry is not None and not drop_last and \
                len(next(iter(carry.values()))) > 0:
            yield _format(carry, batch_format)

    def iter_device_batches(self, batch_size: int = 256, sharding=None,
                            prefetch: int = 2) -> Iterator[Any]:
        """ML-ingest hot path: host batches → jax.device_put (optionally
        sharded over a mesh) on a BACKGROUND thread feeding a bounded
        queue, so the store fetch + H2D transfer overlap the consumer's
        step (reference analogue: iter_torch_batches + pin_memory/
        prefetch worker, data/dataset_iterator.py).  prefetch=0 keeps the
        old inline path; see ray_tpu.data.prefetch.DevicePrefetcher."""
        from ray_tpu.data.prefetch import DevicePrefetcher

        return DevicePrefetcher(self.iter_batches(batch_size, "numpy"),
                                sharding=sharding, prefetch=prefetch)

    def materialize(self) -> "Dataset":
        ray_tpu.wait(self._blocks, num_returns=len(self._blocks))
        return self

    def streaming(self, store_budget: Optional[int] = None,
                  max_inflight_blocks: Optional[int] = None):
        """Switch to the bounded-memory streaming executor over this
        dataset's blocks (ray_tpu.data.streaming.StreamingDataset)."""
        from ray_tpu.data.streaming import StreamingDataset

        thunks = [(lambda r=r: r) for r in self._blocks]
        return StreamingDataset(thunks, store_budget=store_budget,
                                max_inflight_blocks=max_inflight_blocks)

    # ---------------- writes (reference: Dataset.write_parquet/csv/json,
    # python/ray/data/dataset.py + file_datasink.py: one file per block,
    # written by the task that holds the block) ----------------
    def _write(self, path: str, fmt: str, ext: str, mode: str) -> List[str]:
        import glob as glob_mod
        import os

        existing = glob_mod.glob(os.path.join(path, f"part-*.{ext}"))
        if existing:
            if mode == "overwrite":
                for p in existing:
                    os.remove(p)  # a shorter write must not leave a stale
                    # tail that doubles rows on read-back
            else:
                raise FileExistsError(
                    f"{path} already holds {len(existing)} part files; "
                    "pass mode='overwrite' to replace them")
        refs = [
            _write_block.remote(
                b, os.path.join(path, f"part-{i:05d}.{ext}"), fmt)
            for i, b in enumerate(self._blocks)
        ]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str, mode: str = "error") -> List[str]:
        return self._write(path, "parquet", "parquet", mode)

    def write_csv(self, path: str, mode: str = "error") -> List[str]:
        return self._write(path, "csv", "csv", mode)

    def write_json(self, path: str, mode: str = "error") -> List[str]:
        return self._write(path, "json", "json", mode)

    def stats(self) -> dict:
        return {"num_blocks": len(self._blocks), "count": self.count()}


Batch = Union[Dict[str, np.ndarray], Any]


def _format(batch: Dict[str, np.ndarray], batch_format: str):
    if batch_format == "numpy":
        return batch
    if batch_format == "pandas":
        import pandas as pd

        return pd.DataFrame(batch)
    if batch_format == "pyarrow":
        return block_from_numpy(batch)
    raise ValueError(batch_format)


def _batch_to_rows(batch: Dict[str, np.ndarray]) -> List[dict]:
    keys = list(batch)
    n = len(batch[keys[0]]) if keys else 0
    return [{k: batch[k][i] for k in keys} for i in range(n)]


def _rows_to_batch(rows: List[Any]) -> Dict[str, np.ndarray]:
    if rows and isinstance(rows[0], dict):
        return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
    return {"item": np.asarray(rows)}
