"""Background host→device prefetch: the Data→Train ingest hot path.

``iter_device_batches`` used to run ``jax.device_put`` inline on the
consuming thread, so the object-store fetch + numpy assembly + H2D enqueue
all serialized with the training step.  :class:`DevicePrefetcher` moves
the whole producer side — block fetch, batch slicing, ``device_put`` —
onto a background thread feeding a bounded queue of device-resident
(optionally sharded) batches, double-buffered by default so the transfer
of batch N+1..N+prefetch overlaps the consumer's compute on batch N
(reference analogue: iter_torch_batches' pin_memory+prefetch worker,
python/ray/data/dataset_iterator.py; the Podracer "keep the device fed"
rule, arXiv:2104.06272).

Since the flow substrate landed this is a thin wrapper over one
:class:`ray_tpu.parallel.flow.Stage` — the bounded queue, producer
thread, error propagation and close/drain semantics all come from flow;
only the ``device_put`` placement policy lives here.

Contract (unchanged from the hand-rolled version):

- ``prefetch=0`` degrades to the old inline behavior — no thread, the
  consumer pays the device_put (useful for debugging and as the
  comparison baseline in tools/perf_smoke.py).
- Producer-thread exceptions propagate to the consumer at the point of
  ``next()`` (original traceback preserved), never silently truncate the
  stream.
- ``close()`` (also called by ``__del__`` and generator-style GC) stops
  and joins the producer thread deterministically — no leaked threads,
  even when the producer is blocked on a full queue.
- Queue occupancy and batch counts export through ray_tpu.util.metrics
  (both the legacy ``data_prefetch_*`` names and the substrate's tagged
  ``flow_*`` series; best-effort, skipped where no driver is connected)
  and per-batch H2D spans land in the ray_tpu._private.profiling span
  recorder as ``prefetch_h2d``.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional


def _make_place_fn(sharding, place_fn):
    if place_fn is not None:
        return place_fn

    def place(batch):
        import jax

        if sharding is not None:
            return jax.device_put(batch, sharding)
        return jax.device_put(batch)

    return place


class DevicePrefetcher(Iterator[Any]):
    """Iterator of device-resident batches with background H2D transfer.

    ``host_batches``: any iterable of host batches (dict-of-numpy or
    pytree).  ``sharding``: placement for ``jax.device_put`` (None =
    default device).  ``place_fn``: overrides placement entirely (takes a
    host batch, returns the device batch).  ``prefetch``: bounded queue
    size (device batches materialized ahead of the consumer); 0 = inline.
    """

    def __init__(self, host_batches: Iterable[Any], sharding=None,
                 prefetch: int = 2,
                 place_fn: Optional[Callable[[Any], Any]] = None,
                 name: str = "device-prefetch"):
        from ray_tpu.parallel.flow import Stage  # lazy: parallel pulls jax

        self.prefetch = int(prefetch)
        self._stage = Stage(
            host_batches, _make_place_fn(sharding, place_fn),
            depth=max(1, self.prefetch),
            workers=1 if self.prefetch > 0 else 0,
            name=name, span="prefetch_h2d",
            # flow's throttled export is kept; the legacy gauge names are
            # exported once at end-of-stream/close below.
            export_metrics=True)
        self._exported = False

    # ---- consumer side ----
    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self):
        try:
            return next(self._stage)
        except BaseException:
            self._export_metrics()
            raise

    # ---- lifecycle ----
    def close(self):
        """Stop the producer and join its thread.  Idempotent; safe to
        call mid-stream (pending device batches are dropped)."""
        self._stage.close()
        self._export_metrics()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, exc_type, exc_val, tb):
        self.close()

    # ---- observability ----
    @property
    def _thread(self):
        """The producer thread (None once joined / in inline mode) —
        part of the de-facto API: tests assert its lifecycle."""
        threads = self._stage.worker_threads
        return threads[0] if threads else None

    @property
    def peak_occupancy(self) -> int:
        return self._stage.peak_occupancy

    @property
    def batches_delivered(self) -> int:
        return self._stage.items_delivered

    def _export_metrics(self):
        if self._exported:
            return
        self._exported = True
        try:
            from ray_tpu.util.metrics import Counter, Gauge

            Counter("data_prefetch_batches_total",
                    "device batches delivered by the prefetch queue"
                    ).inc(self.batches_delivered)
            Gauge("data_prefetch_queue_peak",
                  "peak occupancy of the device prefetch queue"
                  ).set(float(self.peak_occupancy))
        except Exception:
            pass  # no connected driver (e.g. bare worker process)


def iter_device_batches(host_batches: Iterable[Any], sharding=None,
                        prefetch: int = 2,
                        place_fn: Optional[Callable[[Any], Any]] = None
                        ) -> DevicePrefetcher:
    """Functional form: wrap any host-batch iterable in a background
    device prefetcher (see :class:`DevicePrefetcher`)."""
    return DevicePrefetcher(host_batches, sharding=sharding,
                            prefetch=prefetch, place_fn=place_fn)
