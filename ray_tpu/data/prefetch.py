"""Background host→device prefetch: the Data→Train ingest hot path.

``iter_device_batches`` used to run ``jax.device_put`` inline on the
consuming thread, so the object-store fetch + numpy assembly + H2D enqueue
all serialized with the training step.  :class:`DevicePrefetcher` moves
the whole producer side — block fetch, batch slicing, ``device_put`` —
onto a background thread feeding a bounded queue of device-resident
(optionally sharded) batches, double-buffered by default so the transfer
of batch N+1..N+prefetch overlaps the consumer's compute on batch N
(reference analogue: iter_torch_batches' pin_memory+prefetch worker,
python/ray/data/dataset_iterator.py; the Podracer "keep the device fed"
rule, arXiv:2104.06272).

Contract:

- ``prefetch=0`` degrades to the old inline behavior — no thread, the
  consumer pays the device_put (useful for debugging and as the
  comparison baseline in tools/perf_smoke.py).
- Producer-thread exceptions propagate to the consumer at the point of
  ``next()`` (original traceback preserved), never silently truncate the
  stream.
- ``close()`` (also called by ``__del__`` and generator-style GC) stops
  and joins the producer thread deterministically — no leaked threads,
  even when the producer is blocked on a full queue.
- Queue occupancy and batch counts export through ray_tpu.util.metrics
  (best-effort; skipped where no driver is connected) and per-batch H2D
  spans land in the ray_tpu._private.profiling span recorder.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional


class _EndOfStream:
    """Producer→consumer sentinel; carries the producer's exception (or
    None for a clean end of stream)."""
    __slots__ = ("error",)

    def __init__(self, error: Optional[BaseException] = None):
        self.error = error


def _make_place_fn(sharding, place_fn):
    if place_fn is not None:
        return place_fn

    def place(batch):
        import jax

        if sharding is not None:
            return jax.device_put(batch, sharding)
        return jax.device_put(batch)

    return place


def _bounded_put(q: "queue.Queue", stop: threading.Event, item) -> bool:
    """Bounded-queue put that aborts promptly on close() — the producer
    must never be stranded on a full queue the consumer abandoned."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _produce(src, q: "queue.Queue", stop: threading.Event, place):
    """Producer thread body.  Deliberately a MODULE-LEVEL function taking
    its state as arguments: a bound-method target would make the running
    thread keep the DevicePrefetcher alive, so consumer-side GC could
    never trigger __del__/close and the thread would leak."""
    from ray_tpu._private import profiling

    error: Optional[BaseException] = None
    try:
        for batch in src:
            if stop.is_set():
                return
            t0 = time.perf_counter()
            dev = place(batch)
            profiling.record_span("prefetch_h2d", t0, time.perf_counter())
            if not _bounded_put(q, stop, dev):
                return
    except BaseException as e:  # noqa: BLE001 — shipped to consumer
        error = e
    finally:
        # The producer thread owns the source iterator: release its
        # upstream resources (object-store refs held by the block
        # iterator) here, where the generator is not mid-execution.
        close = getattr(src, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass
        _bounded_put(q, stop, _EndOfStream(error))


class DevicePrefetcher(Iterator[Any]):
    """Iterator of device-resident batches with background H2D transfer.

    ``host_batches``: any iterable of host batches (dict-of-numpy or
    pytree).  ``sharding``: placement for ``jax.device_put`` (None =
    default device).  ``place_fn``: overrides placement entirely (takes a
    host batch, returns the device batch).  ``prefetch``: bounded queue
    size (device batches materialized ahead of the consumer); 0 = inline.
    """

    def __init__(self, host_batches: Iterable[Any], sharding=None,
                 prefetch: int = 2,
                 place_fn: Optional[Callable[[Any], Any]] = None,
                 name: str = "device-prefetch"):
        self._src = iter(host_batches)
        self._place = _make_place_fn(sharding, place_fn)
        self.prefetch = int(prefetch)
        self._count = 0
        self._peak_occupancy = 0
        self._end: Optional[_EndOfStream] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._q: Optional["queue.Queue"] = None
        if self.prefetch > 0:
            self._q = queue.Queue(maxsize=self.prefetch)
            self._thread = threading.Thread(
                target=_produce, args=(self._src, self._q, self._stop,
                                       self._place),
                daemon=True, name=f"rtpu-{name}")
            self._thread.start()

    # ---- consumer side ----
    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self):
        if self._end is not None:
            self._raise_end()
        if self.prefetch <= 0:
            try:
                batch = next(self._src)
            except StopIteration:
                self._end = _EndOfStream()
                self._export_metrics()
                raise
            dev = self._place(batch)
            self._count += 1
            return dev
        while True:
            self._peak_occupancy = max(self._peak_occupancy,
                                       self._q.qsize())
            try:
                item = self._q.get(timeout=0.5)
            except queue.Empty:
                if self._thread is not None and not self._thread.is_alive():
                    # Defensive: the producer always enqueues a sentinel in
                    # its finally, so this means the thread was killed hard.
                    self._end = _EndOfStream(
                        RuntimeError("prefetch producer thread died"))
                    self._raise_end()
                continue
            if isinstance(item, _EndOfStream):
                self._end = item
                self._export_metrics()
                self._raise_end()
            self._count += 1
            return item

    def _raise_end(self):
        if self._end.error is not None:
            raise self._end.error
        raise StopIteration

    # ---- lifecycle ----
    def close(self):
        """Stop the producer and join its thread.  Idempotent; safe to
        call mid-stream (pending device batches are dropped)."""
        self._stop.set()
        if self._q is not None:
            # Unblock a producer waiting on a full queue.
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._end is None:
            self._end = _EndOfStream()
            self._export_metrics()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, exc_type, exc_val, tb):
        self.close()

    @property
    def peak_occupancy(self) -> int:
        return self._peak_occupancy

    @property
    def batches_delivered(self) -> int:
        return self._count

    def _export_metrics(self):
        try:
            from ray_tpu.util.metrics import Counter, Gauge

            Counter("data_prefetch_batches_total",
                    "device batches delivered by the prefetch queue"
                    ).inc(self._count)
            Gauge("data_prefetch_queue_peak",
                  "peak occupancy of the device prefetch queue"
                  ).set(float(self._peak_occupancy))
        except Exception:
            pass  # no connected driver (e.g. bare worker process)


def iter_device_batches(host_batches: Iterable[Any], sharding=None,
                        prefetch: int = 2,
                        place_fn: Optional[Callable[[Any], Any]] = None
                        ) -> DevicePrefetcher:
    """Functional form: wrap any host-batch iterable in a background
    device prefetcher (see :class:`DevicePrefetcher`)."""
    return DevicePrefetcher(host_batches, sharding=sharding,
                            prefetch=prefetch, place_fn=place_fn)
