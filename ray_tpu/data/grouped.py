"""Distributed sort + groupby over dataset blocks.

Reference: python/ray/data/dataset.py (Dataset.sort, Dataset.groupby),
_internal/sort.py (sample → boundaries → range-partition → per-partition
merge) and grouped_data.py (GroupedData.count/sum/mean/min/max/std via a
hash shuffle + per-partition combine).  Same two-phase shape here, all
block-parallel remote tasks — the driver only routes refs:

  sort:    sample each block → positional boundaries → every block range-
           partitions itself (num_returns=P) → output block i concatenates
           part i of every input and sorts locally.
  groupby: every block hash-partitions itself by key → output partition i
           concatenates its parts and aggregates with pyarrow group_by.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod


def _mask_filter(blk, mask: np.ndarray):
    import pyarrow as pa

    return blk.filter(pa.array(mask.astype(bool)))


@ray_tpu.remote
def _sample_keys(blk, key: str, n: int):
    col = blk.column(key).to_numpy(zero_copy_only=False)
    if len(col) == 0:
        return col
    idx = np.random.default_rng(0).integers(0, len(col), size=min(n, len(col)))
    return col[idx]


@ray_tpu.remote
def _range_partition(blk, key: str, boundaries, descending: bool):
    col = blk.column(key).to_numpy(zero_copy_only=False)
    part = np.searchsorted(np.asarray(boundaries), col, side="right")
    n_parts = len(boundaries) + 1
    if descending:
        part = (n_parts - 1) - part
    return tuple(_mask_filter(blk, part == i) for i in range(n_parts))


@ray_tpu.remote
def _merge_sorted(key: str, descending: bool, *parts):
    t = block_mod.concat_blocks(list(parts))
    col = t.column(key).to_numpy(zero_copy_only=False)
    order = np.argsort(col, kind="stable")
    if descending:
        order = order[::-1]
    return t.take(order)


def _stable_hash(col: np.ndarray) -> np.ndarray:
    """Process-stable per-value hashes (python's str hash is salted per
    process, which would send equal keys to different partitions across
    workers).  Numeric dtypes vectorize through a splitmix64 finalizer;
    objects/strings fall back to crc32 of the repr."""
    if np.issubdtype(col.dtype, np.integer) \
            or np.issubdtype(col.dtype, np.floating):
        # ONE canonical numeric form: arrow promotes an int64 column to
        # float64 when a block holds a null, so int and float paths must
        # agree or the same key hashes differently across blocks and a
        # group splits.  float64 bits lose int uniqueness above 2^53 —
        # keys collide into one partition there, which only skews load,
        # never correctness.
        x = col.astype(np.float64).view(np.uint64)
    else:
        import zlib

        return np.fromiter((zlib.crc32(repr(v).encode()) for v in col),
                           dtype=np.uint64, count=len(col))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


@ray_tpu.remote
def _hash_partition(blk, key: str, num_parts: int):
    col = blk.column(key).to_numpy(zero_copy_only=False)
    part = _stable_hash(col) % num_parts
    return tuple(_mask_filter(blk, part == i) for i in range(num_parts))


@ray_tpu.remote
def _agg_partition(key: str, aggs, *parts):
    """aggs: list of (column, pyarrow aggregate name) — output columns get
    pyarrow's '{col}_{fn}' naming."""
    t = block_mod.concat_blocks(list(parts))
    # Empty partitions still go through group_by: it returns zero rows
    # with the AGGREGATED schema, keeping every output block consistent.
    return t.group_by([key]).aggregate(list(aggs))


def sort_impl(blocks: List, key: str, descending: bool = False,
              samples_per_block: int = 64) -> List:
    if not blocks:
        return blocks
    samples = np.concatenate(
        ray_tpu.get([_sample_keys.remote(b, key, samples_per_block)
                     for b in blocks]))
    if samples.size == 0:
        return blocks
    n_out = len(blocks)
    # Positional boundaries from the sorted sample — works for any
    # orderable dtype (strings included), unlike np.quantile.
    samples = np.sort(samples, kind="stable")
    if n_out > 1:
        pos = np.linspace(0, len(samples) - 1, n_out + 1)[1:-1]
        boundaries = samples[pos.astype(int)]
    else:
        boundaries = samples[:0]
    part_lists = [
        _range_partition.options(num_returns=n_out).remote(
            b, key, boundaries, descending)
        for b in blocks
    ]
    if n_out == 1:
        part_lists = [[p] for p in part_lists]
    return [
        _merge_sorted.remote(key, descending,
                             *[parts[i] for parts in part_lists])
        for i in range(n_out)
    ]


class GroupedData:
    """ds.groupby(key) → aggregations (reference: grouped_data.py)."""

    def __init__(self, dataset, key: str,
                 num_partitions: Optional[int] = None):
        self._ds = dataset
        self._key = key
        self._parts = num_partitions or max(
            1, min(8, len(dataset._blocks)))

    def _aggregate(self, aggs):
        from ray_tpu.data.dataset import Dataset

        blocks = self._ds._blocks
        part_lists = [
            _hash_partition.options(num_returns=self._parts).remote(
                b, self._key, self._parts)
            for b in blocks
        ]
        if self._parts == 1:
            part_lists = [[p] for p in part_lists]
        return Dataset([
            _agg_partition.remote(self._key, aggs,
                                  *[parts[i] for parts in part_lists])
            for i in range(self._parts)
        ])

    def count(self):
        return self._aggregate([(self._key, "count")])

    def sum(self, col: str):
        return self._aggregate([(col, "sum")])

    def mean(self, col: str):
        return self._aggregate([(col, "mean")])

    def min(self, col: str):
        return self._aggregate([(col, "min")])

    def max(self, col: str):
        return self._aggregate([(col, "max")])

    def std(self, col: str):
        return self._aggregate([(col, "stddev")])

    def aggregate(self, *aggs):
        """aggs: (column, pyarrow_agg_name) pairs, e.g. ("v", "sum")."""
        return self._aggregate(list(aggs))
