"""Streaming plan execution for the eager Dataset API.

Reference: ray.data's lazy ExecutionPlan + StreamingExecutor
(python/ray/data/_internal/plan.py, _internal/execution/
streaming_executor.py:31).  ``Dataset.map_batches``/``map``/``filter``
no longer submit one task per block at call time — they append *plan
ops* to a lazy logical plan, and consumption drives the plan through a
bounded :class:`ray_tpu.parallel.flow.RefStream`:

- one fused task per block applies the WHOLE op chain (read included for
  lazy read sources), so a read→map→filter pipeline costs one store
  write per block instead of one per stage;
- at most ``window`` output blocks are in flight/resident at once
  (read→map→consume overlap with peak store residency bounded by the
  window, not the dataset);
- results are byte-identical to the old eager engine because both run
  the same per-block kernels (:func:`apply_op`), in the same block
  order.

Plan ops are ``(kind, fn, batch_format)`` tuples — the exact stage
format ``data/streaming.py`` already uses, so an eager Dataset converts
to a StreamingDataset without re-encoding its plan.  Kinds:

- ``"map_batches"`` — ``apply_batch_fn`` over the block;
- ``"filter"`` — pyarrow compute expression (vectorized) or row UDF;
- ``"map_batches_indexed"`` — like map_batches but ``fn(batch,
  block_index)``; carries per-block context (e.g. decorrelated shuffle
  seeds) without a task per distinct closure.
"""
from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import ray_tpu
from ray_tpu.data.block import apply_batch_fn

# NOTE: ray_tpu.parallel.flow is imported lazily inside the executor —
# the parallel package init pulls jax, and the data plane must stay
# importable (worker-side) without it.

# Default bound on in-flight/resident output blocks for plan-driving
# consumers (iter_batches / count / take).  Small enough that a laptop
# store never holds a dataset, large enough to keep a 4-way task pool
# busy; callers override per call.
DEFAULT_WINDOW = 4

PlanOp = Tuple[str, Any, Optional[str]]


def apply_op(blk, op: PlanOp, block_index: int = 0):
    """Apply ONE plan op to a block — the single per-block kernel both
    the eager plan executor and the StreamingDataset run, which is what
    makes streaming results byte-identical to eager ones."""
    kind, fn, batch_format = op
    if kind == "map_batches":
        return apply_batch_fn(blk, fn, batch_format)
    if kind == "map_batches_indexed":
        return apply_batch_fn(blk, lambda b: fn(b, block_index),
                              batch_format)
    if kind == "filter":
        import pyarrow as pa
        import pyarrow.compute as pc

        if isinstance(fn, pc.Expression):
            # Vectorized fast path: the predicate compiles to arrow
            # compute kernels, no Python per row.
            return blk.filter(fn)
        # Row UDF: evaluate over zipped column values — same contract,
        # but no to_pylist() dict materialization per row.
        cols = {name: blk.column(name).to_pylist()
                for name in blk.column_names}
        names = list(cols)
        mask = [bool(fn(dict(zip(names, vals))))
                for vals in zip(*cols.values())] if names else []
        return blk.filter(pa.array(mask, type=pa.bool_()))
    raise ValueError(f"unknown plan op {kind!r}")


def apply_ops(blk, ops: Sequence[PlanOp], block_index: int = 0):
    for op in ops:
        blk = apply_op(blk, op, block_index)
    return blk


@ray_tpu.remote
def _apply_ops_task(blk, ops, block_index):
    return apply_ops(blk, ops, block_index)


@ray_tpu.remote
def _read_apply_ops_task(reader, path, columns, ops, block_index):
    """Operator fusion with the read: the block is born, transformed and
    sealed in ONE task — the Read→MapBatches fusion from the reference's
    logical optimizer (data/_internal/logical/optimizers.py)."""
    return apply_ops(reader(path, columns), ops, block_index)


@ray_tpu.remote
def _count_after_ops(blk, ops, block_index):
    """Count-only consumption: the transformed block lives and dies
    inside this task; only the row count crosses the store."""
    return apply_ops(blk, ops, block_index).num_rows


@ray_tpu.remote
def _read_count_after_ops(reader, path, columns, ops, block_index):
    return apply_ops(reader(path, columns), ops, block_index).num_rows


def is_read_source(src) -> bool:
    return isinstance(src, tuple) and len(src) == 4 and src[0] == "read"


def _submit_thunk(src, ops: List[PlanOp], idx: int) -> Callable[[], Any]:
    """One submit thunk per block for the RefStream: read sources fuse
    read+ops into one task; ref sources chain ops in one task; a ref
    with no ops passes through untouched (no task, no copy)."""
    if is_read_source(src):
        _, reader, path, columns = src
        return lambda: _read_apply_ops_task.remote(reader, path, columns,
                                                   ops, idx)
    if ops:
        return lambda: _apply_ops_task.remote(src, ops, idx)
    return lambda: src


def _count_thunk(src, ops: List[PlanOp], idx: int) -> Callable[[], Any]:
    if is_read_source(src):
        _, reader, path, columns = src
        return lambda: _read_count_after_ops.remote(reader, path, columns,
                                                    ops, idx)
    return lambda: _count_after_ops.remote(src, ops, idx)


class PlanExecutor:
    """Drive a (sources, plan) pair as a bounded pipelined block stream.

    ``iter_block_refs`` yields output block refs in source order with at
    most ``window`` in flight; the caller must drop each yielded ref once
    consumed to release its store copy (the StreamingDataset contract).
    ``last_stream_stats`` exposes the flow stage's counters so smokes and
    tests can assert the residency bound without guessing."""

    def __init__(self, sources: Sequence[Any], plan: Sequence[PlanOp],
                 window: Optional[int] = None, name: str = "dataset"):
        self.sources = list(sources)
        self.plan = list(plan)
        self.window = max(1, int(window or DEFAULT_WINDOW))
        self.name = name
        self.last_stream_stats: Optional[dict] = None

    def _drive(self, make_thunk) -> Iterator[Any]:
        from ray_tpu.parallel import flow

        thunks = (make_thunk(src, self.plan, i)
                  for i, src in enumerate(self.sources))
        stream = flow.RefStream(thunks, depth=self.window,
                                name=f"flow_{self.name}")
        try:
            for ref in stream:
                yield ref
                del ref
        finally:
            self.last_stream_stats = stream.stats()
            stream.close()

    def iter_block_refs(self) -> Iterator[Any]:
        return self._drive(_submit_thunk)

    def iter_count_refs(self) -> Iterator[Any]:
        return self._drive(_count_thunk)

    def materialize_refs(self) -> List[Any]:
        """Eager fan-out (the old engine's memory profile): every block's
        fused op chain submitted at once, refs returned in order."""
        return [_submit_thunk(src, self.plan, i)()
                for i, src in enumerate(self.sources)]
