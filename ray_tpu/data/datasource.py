"""Datasource plugin API + built-in file formats.

Reference: python/ray/data/datasource/datasource.py:20 (Datasource /
Reader contract) and the format readers under python/ray/data/datasource/
(parquet_datasource.py, csv_datasource.py, image_datasource.py,
tfrecords_datasource.py, binary_datasource.py).  Here a datasource is a
callable ``(path, columns) -> pyarrow.Table``; ``register_datasource``
adds user formats, and ``Dataset.read``/``read_streaming`` resolve formats
through this one registry so every executor sees the same plugins.

The TFRecord reader is self-contained: it parses the TFRecord framing
(length / masked-crc / payload) and the tf.train.Example protobuf wire
format directly — no tensorflow dependency.
"""
from __future__ import annotations

import glob as glob_mod
import os
import struct
from typing import Any, Callable, Dict, List, Optional

import numpy as np


def _table_from_numpy(cols: Dict[str, np.ndarray]):
    import pyarrow as pa

    return pa.table({k: pa.array(list(v)) if getattr(v, "ndim", 1) > 1
                     else v for k, v in cols.items()})


# ---------------- built-in readers ----------------

def read_parquet_file(path: str, columns=None):
    import pyarrow.parquet as pq

    return pq.read_table(path, columns=columns)


def read_csv_file(path: str, columns=None):
    import pyarrow.csv as pcsv

    t = pcsv.read_csv(path)
    return t.select(columns) if columns else t


def read_json_file(path: str, columns=None):
    import pyarrow.json as pjson

    t = pjson.read_json(path)
    return t.select(columns) if columns else t


def read_numpy_file(path: str, columns=None):
    # block_from_numpy keeps N-D arrays as FixedSizeList + shape metadata
    # so they round-trip through block_to_numpy with dtype/shape intact.
    from ray_tpu.data.block import block_from_numpy

    arr = np.load(path)
    if isinstance(arr, np.lib.npyio.NpzFile):
        return block_from_numpy({k: arr[k] for k in arr.files})
    return block_from_numpy({"data": arr})


def read_text_file(path: str, columns=None):
    import pyarrow as pa

    with open(path, "r", errors="replace") as f:
        lines = f.read().splitlines()
    return pa.table({"text": lines})


def read_binary_file(path: str, columns=None):
    import pyarrow as pa

    with open(path, "rb") as f:
        data = f.read()
    return pa.table({"bytes": pa.array([data], type=pa.binary()),
                     "path": [path]})


def read_image_file(path: str, columns=None):
    """Decode one image to an HWC uint8 array row (reference:
    image_datasource.py — decoded via PIL)."""
    import pyarrow as pa
    from PIL import Image

    with Image.open(path) as im:
        arr = np.asarray(im.convert("RGB"), dtype=np.uint8)
    return pa.table({"image": pa.array([arr.tolist()]),
                     "height": [arr.shape[0]], "width": [arr.shape[1]],
                     "path": [path]})


# ---------------- TFRecord / tf.train.Example ----------------

def _read_varint(buf: bytes, pos: int):
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _parse_fields(buf: bytes):
    """Yield (field_number, wire_type, value) from a protobuf message."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 1:  # 64-bit
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:  # length-delimited
            n, pos = _read_varint(buf, pos)
            val = buf[pos:pos + n]
            pos += n
        elif wire == 5:  # 32-bit
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _parse_feature(buf: bytes):
    """tf.train.Feature: oneof bytes_list=1 / float_list=2 / int64_list=3."""
    for field, _wire, val in _parse_fields(buf):
        vals: List[Any] = []
        if field == 1:  # BytesList.value = 1 (repeated bytes)
            vals = [v for f, _w, v in _parse_fields(val) if f == 1]
        elif field == 2:  # FloatList.value = 1 (repeated float, packed)
            for f, w, v in _parse_fields(val):
                if f == 1 and w == 2:  # packed
                    vals.extend(struct.unpack(f"<{len(v) // 4}f", v))
                elif f == 1:
                    vals.append(struct.unpack("<f", v)[0])
        elif field == 3:  # Int64List.value = 1 (repeated int64, packed)
            for f, w, v in _parse_fields(val):
                if f == 1 and w == 2:
                    pos = 0
                    while pos < len(v):
                        x, pos = _read_varint(v, pos)
                        vals.append(x - (1 << 64) if x >= (1 << 63) else x)
                elif f == 1:
                    vals.append(v - (1 << 64) if v >= (1 << 63) else v)
        return vals
    return []


def _parse_example(buf: bytes) -> Dict[str, Any]:
    """tf.train.Example { Features features = 1 };
    Features { map<string, Feature> feature = 1 }."""
    row: Dict[str, Any] = {}
    for field, _w, val in _parse_fields(buf):
        if field != 1:
            continue
        for f2, _w2, entry in _parse_fields(val):
            if f2 != 1:
                continue
            key, feat = None, b""
            for f3, _w3, v3 in _parse_fields(entry):
                if f3 == 1:
                    key = v3.decode()
                elif f3 == 2:
                    feat = v3
            if key is not None:
                vals = _parse_feature(feat)
                row[key] = vals[0] if len(vals) == 1 else vals
    return row


def read_tfrecord_file(path: str, columns=None):
    """TFRecord framing: uint64 length, uint32 masked-crc(length), payload,
    uint32 masked-crc(payload).  CRCs are skipped (trusted local files)."""
    import pyarrow as pa

    rows: List[Dict[str, Any]] = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            f.read(4)  # length crc
            payload = f.read(length)
            f.read(4)  # payload crc
            rows.append(_parse_example(payload))
    keys: List[str] = []
    for r in rows:
        for k in r:
            if k not in keys:
                keys.append(k)
    if columns:
        keys = [k for k in keys if k in columns]
    return pa.table({k: [r.get(k) for r in rows] for k in keys})


# ---------------- registry ----------------

_DATASOURCES: Dict[str, Callable] = {
    "parquet": read_parquet_file,
    "csv": read_csv_file,
    "json": read_json_file,
    "numpy": read_numpy_file,
    "text": read_text_file,
    "binary": read_binary_file,
    "images": read_image_file,
    "tfrecord": read_tfrecord_file,
}


def register_datasource(fmt: str, reader: Callable):
    """Plug a user format into every read path: ``reader(path, columns)``
    must return a pyarrow.Table (reference: custom Datasource support,
    datasource.py:20)."""
    _DATASOURCES[fmt] = reader


def resolve_datasource(fmt: str) -> Callable:
    try:
        return _DATASOURCES[fmt]
    except KeyError:
        raise ValueError(
            f"unknown format {fmt!r}; known: {sorted(_DATASOURCES)} "
            "(add formats with ray_tpu.data.register_datasource)") from None


def expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        if os.path.isdir(paths):
            out = []
            for root, dirs, files in os.walk(paths):
                dirs[:] = [d for d in dirs if not d.startswith(".")]
                out.extend(os.path.join(root, f) for f in files
                           if not f.startswith("."))
            return sorted(out)
        return sorted(p for p in glob_mod.glob(paths)
                      if os.path.isfile(p)) or [paths]
    return list(paths)
