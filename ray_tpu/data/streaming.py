"""Streaming Dataset executor: bounded-memory operator pipelines.

Reference: ray.data's StreamingExecutor
(python/ray/data/_internal/execution/streaming_executor.py:31 — run the
operator DAG with backpressure against object-store memory) and the
push-based shuffle (_internal/push_based_shuffle.py).

Design (TPU-first, driver-light):

- A StreamingDataset is a list of *source thunks* (each submits one remote
  task producing a block) plus a chain of per-block stages.  Nothing runs
  at build time.
- The executor keeps at most W block-chains in flight.  W comes from a
  byte budget: the first completed block's directory size (req_object_info)
  divides the store budget — true backpressure against store capacity, not
  a guessed constant.
- Per-block stages chain through object refs with NO barrier (the item
  flows stage-to-stage as soon as its predecessor finishes — the
  pipeline-not-barrier rule).  Intermediate refs are dropped immediately
  so each block's scratch memory frees as soon as the next stage consumes
  it; consumed output blocks free as the iterator advances.
- random_shuffle is a window-scoped two-phase shuffle: each block in the
  window partitions its rows into P parts (map side), each output block
  concatenates one part from every input (reduce side), then shuffles
  rows locally.  The driver only ever holds refs — bytes never
  materialize in the driver process.  (Scope note: the shuffle radius is
  the window, not the whole dataset; a full-dataset pass needs
  window_bytes >= dataset size, matching the reference's bulk shuffle.)
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data import execution


@ray_tpu.remote
def _apply_stage(blk, op, block_index):
    # The SAME per-block kernel the eager plan executor runs
    # (data/execution.py): streaming results are byte-identical to eager
    # ones by construction.
    return execution.apply_op(blk, op, block_index)


@ray_tpu.remote
def _fused_read_apply(reader, path: str, columns, stages, block_index):
    """Operator fusion (the logical optimizer's one rewrite that matters
    for this executor): read + every chained per-block stage execute in
    ONE task, so a read→map→filter pipeline costs one store write per
    block instead of one per stage (reference: the Read→MapBatches fusion
    in data/_internal/logical/optimizers.py)."""
    return execution.apply_ops(reader(path, columns), stages, block_index)


@ray_tpu.remote
def _partition_block(blk, num_parts: int, seed: int):
    """Map side of the shuffle: split rows into num_parts random parts."""
    n = blk.num_rows
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, num_parts, n)
    order = np.argsort(assign, kind="stable")
    sorted_assign = assign[order]
    bounds = np.searchsorted(sorted_assign, np.arange(num_parts + 1))
    taken = blk.take(order)
    return tuple(taken.slice(int(a), int(b - a))
                 for a, b in zip(bounds, bounds[1:]))


@ray_tpu.remote
def _combine_parts(seed: int, *parts):
    """Reduce side: concat one part from every mapper, shuffle rows."""
    out = block_mod.concat_blocks(list(parts))
    rng = np.random.default_rng(seed)
    order = rng.permutation(out.num_rows)
    return out.take(order)


@ray_tpu.remote
def _merge_parts(*parts):
    """Push-shuffle merge: fold a round of mapper parts into the
    partition's accumulator (reference: the merge stage of
    push_based_shuffle.py — merges pipeline WITH the map rounds, so
    mapper outputs never pile up as thousands of tiny store objects)."""
    return block_mod.concat_blocks([p for p in parts if p is not None])


@ray_tpu.remote
def _finalize_partition(seed: int, blk):
    if blk is None:
        return block_mod.block_from_items([])
    rng = np.random.default_rng(seed)
    return blk.take(rng.permutation(blk.num_rows))


class StreamingDataset:
    """Lazy, bounded-memory dataset pipeline.

    Build with ``read_streaming``/``from_source_thunks`` or
    ``Dataset.streaming()``; chain ``map_batches``/``filter``/
    ``random_shuffle``; consume with ``iter_batches``/
    ``iter_device_batches``/``count``.
    """

    def __init__(self, source_thunks: List[Callable[[], Any]],
                 stages: Optional[list] = None,
                 store_budget: Optional[int] = None,
                 max_inflight_blocks: Optional[int] = None):
        self._sources = list(source_thunks)
        self._stages = list(stages or [])
        self.store_budget = store_budget or 128 * 1024 * 1024
        self.max_inflight_blocks = max_inflight_blocks

    # ---------------- construction ----------------
    @staticmethod
    def from_source_thunks(thunks, **kw) -> "StreamingDataset":
        return StreamingDataset(thunks, **kw)

    @staticmethod
    def read(paths, fmt: str, columns=None, **kw) -> "StreamingDataset":
        from ray_tpu.data.datasource import expand_paths, resolve_datasource

        reader = resolve_datasource(fmt)
        # Structured descriptors (not opaque thunks) so the planner can
        # fuse the read with downstream per-block stages into one task.
        sources = [("read", reader, p, columns) for p in expand_paths(paths)]
        return StreamingDataset(sources, **kw)

    def _derive(self, stages) -> "StreamingDataset":
        return StreamingDataset(self._sources, stages, self.store_budget,
                                self.max_inflight_blocks)

    def map_batches(self, fn, batch_format: str = "numpy"
                    ) -> "StreamingDataset":
        return self._derive(self._stages + [("map_batches", fn,
                                             batch_format)])

    def filter(self, fn) -> "StreamingDataset":
        return self._derive(self._stages + [("filter", fn, "numpy")])

    def random_shuffle(self, seed: Optional[int] = None,
                       full: bool = False) -> "StreamingDataset":
        """``full=False``: window-scoped two-phase shuffle (mixing radius
        = the in-flight window — cheap, bounded, the right default for
        epoch-style ML shuffling).  ``full=True``: push-based FULL
        shuffle — every output block draws from every input block
        (reference semantics, push_based_shuffle.py); the dataset is
        accumulated across P partition accumulators (spilling past the
        store budget) while scratch stays round-bounded."""
        if self._shuffle_stages:
            # Only shuffles[0] executes; silently dropping a second
            # (possibly full-radius) shuffle would be a wrong-results bug.
            raise ValueError("this pipeline already has a shuffle stage; "
                             "chain at most one random_shuffle")
        kind = "push_shuffle" if full else "shuffle"
        return self._derive(self._stages + [(kind, seed, None)])

    def explain(self) -> str:
        """The logical plan after fusion, one operator per line."""
        per_block = [s[0] for s in self._per_block_stages]
        fused_reads = sum(1 for s in self._sources
                          if isinstance(s, tuple) and s[0] == "read")
        lines = []
        if fused_reads:
            fused = " -> ".join(["read"] + per_block)
            lines.append(f"Fused[{fused}] x{fused_reads} sources "
                         "(1 task/block)")
        else:
            lines.append(f"Sources x{len(self._sources)}")
            for s in per_block:
                lines.append(f"  -> {s} (1 task/block)")
        for s in self._stages:
            if s[0] == "shuffle":
                lines.append("  -> shuffle[window-scoped]")
            elif s[0] == "push_shuffle":
                lines.append("  -> shuffle[push-based, full radius]")
        return "\n".join(lines)

    # ---------------- execution ----------------
    def _window_size(self, first_ref) -> int:
        """Blocks in flight, from the store budget and a measured block
        size (backpressure against capacity, streaming_executor.py:31)."""
        if self.max_inflight_blocks is not None:
            return max(1, self.max_inflight_blocks)
        from ray_tpu._private.worker import global_worker

        info = None
        try:
            info = global_worker.transport.request(
                "object_info", {"oid": first_ref.id})
        except Exception:
            pass
        if not info or not info.get("size"):
            return 4
        # Half the budget: map stages briefly hold input+output per block.
        return max(2, int(self.store_budget * 0.5 // max(1, info["size"])))

    def _chain_source(self, src, block_index: int = 0):
        """Materialize one source with every per-block stage applied:
        structured read sources fuse read+stages into ONE task; opaque
        thunks fall back to a task per stage."""
        stages = self._per_block_stages
        if isinstance(src, tuple) and src[0] == "read":
            _, reader, path, columns = src
            return _fused_read_apply.remote(reader, path, columns, stages,
                                            block_index)
        ref = src()
        for op in stages:
            ref = _apply_stage.remote(ref, op, block_index)
        return ref

    @property
    def _per_block_stages(self):
        return [s for s in self._stages
                if s[0] not in ("shuffle", "push_shuffle")]

    @property
    def _shuffle_stages(self):
        return [s for s in self._stages
                if s[0] in ("shuffle", "push_shuffle")]

    def iter_block_refs(self) -> Iterator[Any]:
        """The executor: yields output block refs, ≤ window in flight
        (a :class:`ray_tpu.parallel.flow.RefStream` holds the bound —
        the hand-rolled window-fill loop this method used to carry).
        The caller must drop each yielded ref to release its memory."""
        from ray_tpu.parallel import flow  # lazy: keeps data jax-free

        shuffles = self._shuffle_stages
        indexed = iter(enumerate(self._sources))
        first = next(indexed, None)
        if first is None:
            return
        first_ref = self._chain_source(first[1], first[0])
        # Measure the first (fused) output block to size the window.
        ray_tpu.wait([first_ref], num_returns=1, timeout=300)
        window = self._window_size(first_ref)
        thunks = (lambda s=s, i=i: self._chain_source(s, i)
                  for i, s in indexed)
        stream = flow.RefStream(thunks, depth=window, prime=[first_ref],
                                name="streaming_data")
        del first_ref
        try:
            if shuffles and shuffles[0][0] == "push_shuffle":
                yield from self._push_shuffle_refs(stream, window,
                                                   shuffles[0][1])
                return
            if not shuffles:
                for ref in stream:
                    yield ref
                    del ref
                return
            # Shuffle: process window-sized groups through the two-phase
            # exchange; outputs stream out under the same in-flight bound.
            seed_base = shuffles[0][1]
            rng = random.Random(seed_base)
            group_idx = 0
            while True:
                group = list(itertools.islice(stream, window))
                if not group:
                    return
                p = len(group)
                seed0 = (seed_base if seed_base is not None
                         else rng.randrange(2**31))
                parted = [
                    _partition_block.options(num_returns=p).remote(
                        b, p, seed0 + group_idx * 100003 + i)
                    for i, b in enumerate(group)]
                if p == 1:
                    parted = [[r] for r in parted]
                del group
                outs = [
                    _combine_parts.remote(
                        seed0 + 7 + group_idx * 100003 + j,
                        *[parted[i][j] for i in range(p)])
                    for j in range(p)]
                del parted
                for ref in outs:
                    yield ref
                    del ref
                outs = None
                group_idx += 1
        finally:
            # Abandoned iteration (dead consumer, early break) releases
            # every in-flight ref — the flow drain contract.
            stream.close()

    def _push_shuffle_refs(self, stream, window, seed_base):
        """Push-based FULL shuffle (reference: push_based_shuffle.py's
        pipelined map+merge rounds).  Map tasks partition each block into
        P parts; after every window-sized round the parts FOLD into P
        per-partition accumulators (one merge task each), so live scratch
        is one round of parts — never the full P x num_blocks part
        matrix.  The accumulators jointly hold the whole dataset (the
        store spills past its budget; a full shuffle cannot emit row one
        until the last input row is seen), and finalize permutes each
        partition into an output block."""
        P = max(1, len(self._sources))
        rng = random.Random(seed_base)
        seed0 = (seed_base if seed_base is not None
                 else rng.randrange(2**31))
        accs: List[Any] = [None] * P
        parts_held: List[List[Any]] = [[] for _ in range(P)]
        blk_idx = 0
        # Fold cadence: merging every round would rewrite the whole
        # accumulated prefix each round (O(dataset x rounds) IO); holding
        # up to ~8 mapped blocks' parts per fold amortizes that while
        # keeping scratch bounded to fold_every rounds of parts.
        fold_every = max(1, 8 // max(1, window))
        rounds_since_fold = 0

        def fold():
            folded = []
            for j in range(P):
                if not parts_held[j]:
                    continue
                prev = [accs[j]] if accs[j] is not None else []
                accs[j] = _merge_parts.remote(*prev, *parts_held[j])
                parts_held[j] = []
                folded.append(accs[j])
            # Barrier: the held part refs die when these merges land.
            if folded:
                ray_tpu.wait(folded, num_returns=len(folded), timeout=600)

        while True:
            batch = list(itertools.islice(stream, window))
            if not batch:
                break
            for b in batch:
                if P == 1:
                    # Single partition: no exchange needed — the block IS
                    # its one part (num_returns=1 would wrap the kernel's
                    # tuple return as a single tuple-valued object).
                    parts_held[0].append(b)
                    blk_idx += 1
                    continue
                parts = _partition_block.options(num_returns=P).remote(
                    b, P, seed0 + blk_idx)
                blk_idx += 1
                for j in range(P):
                    parts_held[j].append(parts[j])
            del batch
            rounds_since_fold += 1
            if rounds_since_fold >= fold_every:
                fold()
                rounds_since_fold = 0
        fold()
        for j in range(P):
            have = [accs[j]] if accs[j] is not None else []
            if not have:
                continue
            out = _finalize_partition.remote(seed0 + 31 + j, accs[j])
            accs[j] = None
            yield out
            del out

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        from ray_tpu.data.dataset import _format

        carry = None
        for ref in self.iter_block_refs():
            blk = ray_tpu.get(ref)
            del ref  # release the store copy once rows are in this process
            batch = block_mod.block_to_numpy(blk)
            del blk
            if carry is not None:
                batch = {k: np.concatenate([carry[k], batch[k]])
                         for k in batch}
            n = len(next(iter(batch.values()))) if batch else 0
            pos = 0
            while n - pos >= batch_size:
                yield _format({k: v[pos:pos + batch_size]
                               for k, v in batch.items()}, batch_format)
                pos += batch_size
            carry = ({k: v[pos:] for k, v in batch.items()}
                     if pos < n else None)
        if carry is not None and not drop_last and \
                len(next(iter(carry.values()))) > 0:
            yield _format(carry, batch_format)

    def iter_device_batches(self, batch_size: int = 256, sharding=None,
                            prefetch: int = 2) -> Iterator[Any]:
        """Device-resident batches with background H2D prefetch: the
        object-store block fetch, batch assembly AND jax.device_put all
        run on a producer thread feeding a bounded queue, so transfer
        overlaps the consumer's step (prefetch=0: old inline behavior).
        The returned iterator supports close() and joins its thread on
        GC — see ray_tpu.data.prefetch."""
        from ray_tpu.data.prefetch import DevicePrefetcher

        return DevicePrefetcher(self.iter_batches(batch_size, "numpy"),
                                sharding=sharding, prefetch=prefetch)

    def count(self) -> int:
        from ray_tpu.data.dataset import _count_block

        total = 0
        for ref in self.iter_block_refs():
            total += ray_tpu.get(_count_block.remote(ref))
            del ref
        return total
