"""Streaming Dataset executor: bounded-memory operator pipelines.

Reference: ray.data's StreamingExecutor
(python/ray/data/_internal/execution/streaming_executor.py:31 — run the
operator DAG with backpressure against object-store memory) and the
push-based shuffle (_internal/push_based_shuffle.py).

Design (TPU-first, driver-light):

- A StreamingDataset is a list of *source thunks* (each submits one remote
  task producing a block) plus a chain of per-block stages.  Nothing runs
  at build time.
- The executor keeps at most W block-chains in flight.  W comes from a
  byte budget: the first completed block's directory size (req_object_info)
  divides the store budget — true backpressure against store capacity, not
  a guessed constant.
- Per-block stages chain through object refs with NO barrier (the item
  flows stage-to-stage as soon as its predecessor finishes — the
  pipeline-not-barrier rule).  Intermediate refs are dropped immediately
  so each block's scratch memory frees as soon as the next stage consumes
  it; consumed output blocks free as the iterator advances.
- random_shuffle is a window-scoped two-phase shuffle: each block in the
  window partitions its rows into P parts (map side), each output block
  concatenates one part from every input (reduce side), then shuffles
  rows locally.  The driver only ever holds refs — bytes never
  materialize in the driver process.  (Scope note: the shuffle radius is
  the window, not the whole dataset; a full-dataset pass needs
  window_bytes >= dataset size, matching the reference's bulk shuffle.)
"""
from __future__ import annotations

import random
from typing import Any, Callable, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod


@ray_tpu.remote
def _apply_stage(blk, kind: str, fn, batch_format: str):
    if kind == "map_batches":
        return block_mod.apply_batch_fn(blk, fn, batch_format)
    if kind == "filter":
        import pyarrow as pa

        mask = [bool(fn(row)) for row in blk.to_pylist()]
        return blk.filter(pa.array(mask))
    raise ValueError(kind)


@ray_tpu.remote
def _partition_block(blk, num_parts: int, seed: int):
    """Map side of the shuffle: split rows into num_parts random parts."""
    n = blk.num_rows
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, num_parts, n)
    order = np.argsort(assign, kind="stable")
    sorted_assign = assign[order]
    bounds = np.searchsorted(sorted_assign, np.arange(num_parts + 1))
    taken = blk.take(order)
    return tuple(taken.slice(int(a), int(b - a))
                 for a, b in zip(bounds, bounds[1:]))


@ray_tpu.remote
def _combine_parts(seed: int, *parts):
    """Reduce side: concat one part from every mapper, shuffle rows."""
    out = block_mod.concat_blocks(list(parts))
    rng = np.random.default_rng(seed)
    order = rng.permutation(out.num_rows)
    return out.take(order)


class StreamingDataset:
    """Lazy, bounded-memory dataset pipeline.

    Build with ``read_streaming``/``from_source_thunks`` or
    ``Dataset.streaming()``; chain ``map_batches``/``filter``/
    ``random_shuffle``; consume with ``iter_batches``/
    ``iter_device_batches``/``count``.
    """

    def __init__(self, source_thunks: List[Callable[[], Any]],
                 stages: Optional[list] = None,
                 store_budget: Optional[int] = None,
                 max_inflight_blocks: Optional[int] = None):
        self._sources = list(source_thunks)
        self._stages = list(stages or [])
        self.store_budget = store_budget or 128 * 1024 * 1024
        self.max_inflight_blocks = max_inflight_blocks

    # ---------------- construction ----------------
    @staticmethod
    def from_source_thunks(thunks, **kw) -> "StreamingDataset":
        return StreamingDataset(thunks, **kw)

    @staticmethod
    def read(paths, fmt: str, columns=None, **kw) -> "StreamingDataset":
        from ray_tpu.data.dataset import _read_file
        from ray_tpu.data.datasource import expand_paths, resolve_datasource

        reader = resolve_datasource(fmt)
        thunks = [(lambda p=p: _read_file.remote(reader, p, columns))
                  for p in expand_paths(paths)]
        return StreamingDataset(thunks, **kw)

    def _derive(self, stages) -> "StreamingDataset":
        return StreamingDataset(self._sources, stages, self.store_budget,
                                self.max_inflight_blocks)

    def map_batches(self, fn, batch_format: str = "numpy"
                    ) -> "StreamingDataset":
        return self._derive(self._stages + [("map_batches", fn,
                                             batch_format)])

    def filter(self, fn) -> "StreamingDataset":
        return self._derive(self._stages + [("filter", fn, "numpy")])

    def random_shuffle(self, seed: Optional[int] = None
                       ) -> "StreamingDataset":
        return self._derive(self._stages + [("shuffle", seed, None)])

    # ---------------- execution ----------------
    def _window_size(self, first_ref) -> int:
        """Blocks in flight, from the store budget and a measured block
        size (backpressure against capacity, streaming_executor.py:31)."""
        if self.max_inflight_blocks is not None:
            return max(1, self.max_inflight_blocks)
        from ray_tpu._private.worker import global_worker

        info = None
        try:
            info = global_worker.transport.request(
                "object_info", {"oid": first_ref.id})
        except Exception:
            pass
        if not info or not info.get("size"):
            return 4
        # Half the budget: map stages briefly hold input+output per block.
        return max(2, int(self.store_budget * 0.5 // max(1, info["size"])))

    def _chain(self, ref):
        """Apply per-block stages (up to but excluding any shuffle) to one
        source ref, dropping intermediate refs as we go."""
        for kind, fn, batch_format in self._per_block_stages:
            ref = _apply_stage.remote(ref, kind, fn, batch_format)
        return ref

    @property
    def _per_block_stages(self):
        return [s for s in self._stages if s[0] != "shuffle"]

    @property
    def _shuffle_stages(self):
        return [s for s in self._stages if s[0] == "shuffle"]

    def iter_block_refs(self) -> Iterator[Any]:
        """The executor: yields output block refs, ≤ window in flight.
        The caller must drop each yielded ref to release its memory."""
        shuffles = self._shuffle_stages
        pending: List[Any] = []
        window: Optional[int] = None
        sources = iter(self._sources)
        first = next(sources, None)
        if first is None:
            return
        first_src_ref = first()
        # Measure the first block to size the window (waits for it).
        ray_tpu.wait([first_src_ref], num_returns=1, timeout=300)
        window = self._window_size(first_src_ref)
        pending.append(self._chain(first_src_ref))
        del first_src_ref

        def fill():
            while len(pending) < window:
                thunk = next(sources, None)
                if thunk is None:
                    return False
                pending.append(self._chain(thunk()))
            return True

        if not shuffles:
            fill()
            while pending:
                ref = pending.pop(0)
                yield ref
                del ref
                fill()
            return
        # Shuffle: process window-sized groups through the two-phase
        # exchange; outputs stream out under the same in-flight bound.
        seed_base = shuffles[0][1]
        rng = random.Random(seed_base)
        group_idx = 0
        while True:
            fill()
            if not pending:
                return
            group, pending = pending, []
            p = len(group)
            seed0 = (seed_base if seed_base is not None
                     else rng.randrange(2**31))
            parted = [
                _partition_block.options(num_returns=p).remote(
                    b, p, seed0 + group_idx * 100003 + i)
                for i, b in enumerate(group)]
            if p == 1:
                parted = [[r] for r in parted]
            del group
            outs = [
                _combine_parts.remote(seed0 + 7 + group_idx * 100003 + j,
                                      *[parted[i][j] for i in range(p)])
                for j in range(p)]
            del parted
            for ref in outs:
                yield ref
                del ref
            outs = None
            group_idx += 1

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        from ray_tpu.data.dataset import _format

        carry = None
        for ref in self.iter_block_refs():
            blk = ray_tpu.get(ref)
            del ref  # release the store copy once rows are in this process
            batch = block_mod.block_to_numpy(blk)
            del blk
            if carry is not None:
                batch = {k: np.concatenate([carry[k], batch[k]])
                         for k in batch}
            n = len(next(iter(batch.values()))) if batch else 0
            pos = 0
            while n - pos >= batch_size:
                yield _format({k: v[pos:pos + batch_size]
                               for k, v in batch.items()}, batch_format)
                pos += batch_size
            carry = ({k: v[pos:] for k, v in batch.items()}
                     if pos < n else None)
        if carry is not None and not drop_last and \
                len(next(iter(carry.values()))) > 0:
            yield _format(carry, batch_format)

    def iter_device_batches(self, batch_size: int = 256, sharding=None,
                            prefetch: int = 2) -> Iterator[Any]:
        import collections

        import jax

        q: "collections.deque" = collections.deque()
        for host_batch in self.iter_batches(batch_size, "numpy"):
            dev = (jax.device_put(host_batch, sharding)
                   if sharding is not None else jax.device_put(host_batch))
            q.append(dev)
            if len(q) > prefetch:
                yield q.popleft()
        while q:
            yield q.popleft()

    def count(self) -> int:
        from ray_tpu.data.dataset import _count_block

        total = 0
        for ref in self.iter_block_refs():
            total += ray_tpu.get(_count_block.remote(ref))
            del ref
        return total
