"""Preprocessors (reference: python/ray/data/preprocessors/ — fit/transform
over datasets, attached to trainers via DatasetConfig)."""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        return ds.map_batches(self._transform_numpy, batch_format="numpy")

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def _fit(self, ds):
        pass

    def _transform_numpy(self, batch: Dict[str, np.ndarray]):
        raise NotImplementedError


class BatchMapper(Preprocessor):
    def __init__(self, fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]):
        self.fn = fn

    def _transform_numpy(self, batch):
        return self.fn(batch)


class StandardScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds):
        sums: Dict[str, float] = {c: 0.0 for c in self.columns}
        sqs: Dict[str, float] = {c: 0.0 for c in self.columns}
        n = 0
        for batch in ds.iter_batches(batch_format="numpy"):
            first = True
            for c in self.columns:
                v = batch[c].astype(np.float64)
                sums[c] += v.sum()
                sqs[c] += (v ** 2).sum()
                if first:
                    n += len(v)
                    first = False
        for c in self.columns:
            mean = sums[c] / max(n, 1)
            var = max(sqs[c] / max(n, 1) - mean ** 2, 1e-12)
            self.stats[c] = (mean, var ** 0.5)

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats[c]
            out[c] = (batch[c] - mean) / std
        return out
