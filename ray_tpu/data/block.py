"""Blocks: the unit of distributed data (reference: python/ray/data/block.py
— Arrow/pandas/py-list partitions living in the object store).

A block here is a pyarrow.Table (canonical), with converters to/from numpy
batches and pandas.  Blocks travel as ObjectRefs; pyarrow's pickle path is
buffer-based so the store's zero-copy read applies.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np
import pyarrow as pa

Batch = Union[Dict[str, np.ndarray], "pa.Table"]


def block_from_items(items: List[Any]) -> pa.Table:
    if items and isinstance(items[0], dict):
        cols = {k: [it[k] for it in items] for k in items[0]}
        return pa.table(cols)
    return pa.table({"item": items})


def block_from_numpy(arrays: Dict[str, np.ndarray]) -> pa.Table:
    cols = {}
    for k, v in arrays.items():
        v = np.asarray(v)
        if v.ndim <= 1:
            cols[k] = pa.array(v)
        else:
            # Fixed-shape tensors: flatten rows into FixedSizeList.
            flat = v.reshape(len(v), -1)
            cols[k] = pa.FixedSizeListArray.from_arrays(
                pa.array(flat.ravel()), flat.shape[1])
            cols[k] = pa.chunked_array([cols[k]])
    t = pa.table(cols)
    meta = {f"shape:{k}": ",".join(map(str, np.asarray(v).shape[1:]))
            for k, v in arrays.items() if np.asarray(v).ndim > 1}
    if meta:
        t = t.replace_schema_metadata(
            {**(t.schema.metadata or {}),
             **{k.encode(): v.encode() for k, v in meta.items()}})
    return t


def block_to_numpy(block: pa.Table) -> Dict[str, np.ndarray]:
    out = {}
    meta = block.schema.metadata or {}
    for name in block.column_names:
        col = block.column(name)
        arr = col.combine_chunks()
        if pa.types.is_fixed_size_list(arr.type):
            flat = np.asarray(arr.values)
            shape_meta = meta.get(f"shape:{name}".encode())
            inner = (tuple(int(x) for x in shape_meta.decode().split(","))
                     if shape_meta else (arr.type.list_size,))
            out[name] = flat.reshape((len(block),) + inner)
        else:
            out[name] = np.asarray(arr)
    return out


def block_num_rows(block: pa.Table) -> int:
    return block.num_rows


def block_slice(block: pa.Table, start: int, end: int) -> pa.Table:
    return block.slice(start, end - start)


def concat_blocks(blocks: List[pa.Table]) -> pa.Table:
    blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
    return pa.concat_tables(blocks, promote_options="default")


def apply_batch_fn(block: pa.Table, fn, batch_format: str) -> pa.Table:
    """Run a user map_batches fn over one block."""
    if batch_format == "numpy":
        result = fn(block_to_numpy(block))
        if isinstance(result, dict):
            return block_from_numpy(result)
        if isinstance(result, pa.Table):
            return result
        raise TypeError("numpy-format fn must return dict or Table")
    if batch_format == "pandas":
        result = fn(block.to_pandas())
        return pa.Table.from_pandas(result)
    if batch_format == "pyarrow":
        return fn(block)
    raise ValueError(f"bad batch_format {batch_format!r}")
