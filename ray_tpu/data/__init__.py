"""Ray-Data-equivalent distributed datasets (reference: python/ray/data/)."""
from typing import Any, Dict, List, Union

import numpy as np

from ray_tpu.data.dataset import Dataset  # noqa: F401
from ray_tpu.data.preprocessors import (  # noqa: F401
    BatchMapper,
    Preprocessor,
    StandardScaler,
)
from ray_tpu.data.datasource import register_datasource  # noqa: F401
from ray_tpu.data.grouped import GroupedData  # noqa: F401
from ray_tpu.data.prefetch import DevicePrefetcher  # noqa: F401
from ray_tpu.data.streaming import StreamingDataset  # noqa: F401


def read_streaming(paths, fmt: str, columns=None, **kw) -> "StreamingDataset":
    """Bounded-memory streaming read (reference: the streaming executor
    path, data/_internal/execution/streaming_executor.py:31)."""
    return StreamingDataset.read(paths, fmt, columns, **kw)


def from_items(items: List[Any], parallelism: int = 8) -> Dataset:
    return Dataset.from_items(items, parallelism)


def range(n: int, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset.range(n, parallelism)


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]],
               parallelism: int = 8) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {"data": arrays}
    return Dataset.from_numpy(arrays, parallelism)


def read_parquet(paths, columns=None) -> Dataset:
    return Dataset.read(paths, "parquet", columns)


def read_csv(paths) -> Dataset:
    return Dataset.read(paths, "csv")


def read_json(paths) -> Dataset:
    return Dataset.read(paths, "json")


def read_numpy(paths) -> Dataset:
    return Dataset.read(paths, "numpy")


def read_text(paths) -> Dataset:
    return Dataset.read(paths, "text")


def read_binary_files(paths) -> Dataset:
    return Dataset.read(paths, "binary")


def read_images(paths) -> Dataset:
    return Dataset.read(paths, "images")


def read_tfrecords(paths, columns=None) -> Dataset:
    return Dataset.read(paths, "tfrecord", columns)


def read_datasource(fmt: str, paths, columns=None) -> Dataset:
    """Read through a registered plugin format (register_datasource)."""
    return Dataset.read(paths, fmt, columns)
