"""Job submission: run driver scripts against the cluster and track them.

Reference: dashboard/modules/job/job_manager.py:490 (JobManager driving
entrypoint subprocesses with status + log capture) and
python/ray/dashboard/modules/job/sdk.py (JobSubmissionClient over the
dashboard's REST API).  Same split here: a ``JobManager`` embedded in the
head process spawns ``sh -c entrypoint`` subprocesses whose env carries
the head's TCP address + authkey (so the entrypoint's
``ray_tpu.init(address=...)`` joins this cluster), logs go to the session
log dir (tailed by the dashboard), and ``JobSubmissionClient`` talks
either directly to the in-process manager or over HTTP to a remote
dashboard.
"""
from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from typing import Dict, List, Optional
from urllib.request import Request, urlopen


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class JobManager:
    def __init__(self, head):
        self.head = head
        self._jobs: Dict[str, dict] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self.logs_dir = os.path.join(head.session_dir, "logs")
        os.makedirs(self.logs_dir, exist_ok=True)

    def submit(self, entrypoint: str, submission_id: Optional[str] = None,
               runtime_env: Optional[dict] = None,
               metadata: Optional[dict] = None) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id} already exists")
            self._jobs[job_id] = {
                "job_id": job_id, "entrypoint": entrypoint,
                "status": JobStatus.PENDING, "metadata": metadata or {},
                "start_time": time.time(), "end_time": None,
                "message": "", "log_file": f"job-{job_id}.log",
            }
        env = dict(os.environ)
        from ray_tpu._private import inject_pkg_pythonpath

        inject_pkg_pythonpath(env)
        env["RAY_TPU_ADDRESS"] = f"127.0.0.1:{self.head.tcp_port}"
        env["RAY_TPU_AUTHKEY"] = self.head.authkey.hex()
        env["RAY_TPU_JOB_ID"] = job_id
        for k, v in ((runtime_env or {}).get("env_vars") or {}).items():
            env[k] = str(v)
        log_path = os.path.join(self.logs_dir, f"job-{job_id}.log")
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                ["/bin/sh", "-c", entrypoint],
                env=env,
                cwd=(runtime_env or {}).get("working_dir") or os.getcwd(),
                stdout=log_f, stderr=subprocess.STDOUT)
        except OSError as e:
            with self._lock:
                self._jobs[job_id].update(status=JobStatus.FAILED,
                                          message=str(e),
                                          end_time=time.time())
            return job_id
        finally:
            log_f.close()
        with self._lock:
            self._jobs[job_id]["status"] = JobStatus.RUNNING
            self._procs[job_id] = proc
        threading.Thread(target=self._wait, args=(job_id, proc),
                         name=f"rtpu-job-{job_id}", daemon=True).start()
        return job_id

    def _wait(self, job_id: str, proc: subprocess.Popen):
        rc = proc.wait()
        with self._lock:
            info = self._jobs[job_id]
            if info["status"] == JobStatus.STOPPED:
                pass  # stop() already finalized
            elif rc == 0:
                info["status"] = JobStatus.SUCCEEDED
            else:
                info["status"] = JobStatus.FAILED
                info["message"] = f"entrypoint exited with code {rc}"
            info["end_time"] = time.time()
            self._procs.pop(job_id, None)

    def stop(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            if proc is None:
                return False
            self._jobs[job_id]["status"] = JobStatus.STOPPED
            self._jobs[job_id]["message"] = "stopped by user"
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
        return True

    def get_job(self, job_id: str) -> Optional[dict]:
        with self._lock:
            info = self._jobs.get(job_id)
            return dict(info) if info else None

    def list_jobs(self) -> List[dict]:
        with self._lock:
            return [dict(v) for v in self._jobs.values()]

    def get_logs(self, job_id: str) -> str:
        info = self.get_job(job_id)
        if info is None:
            return ""
        path = os.path.join(self.logs_dir, info["log_file"])
        if not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode(errors="replace")


def _manager(head, create: bool = True) -> Optional[JobManager]:
    """The per-head JobManager singleton (attached lazily)."""
    mgr = getattr(head, "_job_manager", None)
    if mgr is None and create:
        mgr = JobManager(head)
        head._job_manager = mgr
    return mgr


class JobSubmissionClient:
    """Submit/inspect jobs. ``address=None`` drives the in-process head;
    ``address="http://host:port"`` talks to a remote dashboard's REST API
    (reference: job sdk over the dashboard agent)."""

    def __init__(self, address: Optional[str] = None):
        self.address = address.rstrip("/") if address else None
        if self.address is None:
            import ray_tpu

            if ray_tpu._head is None:
                raise RuntimeError(
                    "JobSubmissionClient() without address requires a local "
                    "head; call ray_tpu.init() or pass the dashboard URL")
            self._mgr = _manager(ray_tpu._head)

    def _http(self, method: str, path: str, payload: Optional[dict] = None):
        data = json.dumps(payload or {}).encode() if method == "POST" else None
        req = Request(self.address + path, data=data, method=method,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=30) as resp:
            body = resp.read()
        ctype = resp.headers.get("Content-Type", "")
        return json.loads(body) if "json" in ctype else body.decode()

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[dict] = None) -> str:
        if self.address:
            return self._http("POST", "/api/jobs", {
                "entrypoint": entrypoint, "submission_id": submission_id,
                "runtime_env": runtime_env, "metadata": metadata,
            })["job_id"]
        return self._mgr.submit(entrypoint, submission_id, runtime_env,
                                metadata)

    def get_job_status(self, job_id: str) -> str:
        return self.get_job_info(job_id)["status"]

    def get_job_info(self, job_id: str) -> dict:
        if self.address:
            return self._http("GET", f"/api/jobs/{job_id}")
        info = self._mgr.get_job(job_id)
        if info is None:
            raise ValueError(f"no such job: {job_id}")
        return info

    def get_job_logs(self, job_id: str) -> str:
        if self.address:
            return self._http("GET", f"/api/jobs/{job_id}/logs")
        return self._mgr.get_logs(job_id)

    def list_jobs(self) -> List[dict]:
        if self.address:
            return self._http("GET", "/api/jobs")
        return self._mgr.list_jobs()

    def stop_job(self, job_id: str) -> bool:
        if self.address:
            return self._http("POST", f"/api/jobs/{job_id}/stop")["stopped"]
        return self._mgr.stop(job_id)

    def tail_job_logs(self, job_id: str, timeout: float = 300.0,
                      poll: float = 0.5):
        """Generator yielding log increments until the job finishes."""
        seen = 0
        deadline = time.time() + timeout
        while time.time() < deadline:
            logs = self.get_job_logs(job_id)
            if len(logs) > seen:
                yield logs[seen:]
                seen = len(logs)
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                          JobStatus.STOPPED):
                rest = self.get_job_logs(job_id)
                if len(rest) > seen:
                    yield rest[seen:]
                return
            time.sleep(poll)
