"""Runtime context (reference: python/ray/runtime_context.py)."""
from __future__ import annotations

from typing import Optional


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    @property
    def job_id(self):
        return self._worker.job_id

    @property
    def node_id(self):
        return self._worker.node_id

    def get_task_id(self) -> Optional[str]:
        t = self._worker.ctx.task_id
        return t.hex() if t else None

    def get_actor_id(self) -> Optional[str]:
        for actor_id in self._worker.actors:
            return actor_id.hex()
        return None

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_node_id(self) -> str:
        return self._worker.node_id.hex()


def get_runtime_context() -> RuntimeContext:
    from ray_tpu._private.worker import global_worker

    if global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return RuntimeContext(global_worker)
