"""Cluster dashboard: an HTTP server over the state API, metrics, logs,
and job submission.

Reference: dashboard/head.py (the aiohttp DashboardHead hosting module
routes) + dashboard/modules/{node,actor,job,metrics}. The TPU redesign
collapses the reference's multi-process dashboard (head process + per-node
agents + grpc datapath) into one stdlib ThreadingHTTPServer embedded in
the head process: the head already holds cluster state in-process, so
routes read it directly instead of fanning out RPCs.

Routes (JSON unless noted):
  GET  /api/cluster            — total + available resources, node count
  GET  /api/nodes|actors|tasks|objects|jobs|named_actors
  GET  /state/<what>           — same tables, reference-style path
  GET  /api/summary            — task/actor/object rollups
  GET  /traces                 — tracing plane: stored traces (biggest 1st)
  GET  /timeline?trace_id=     — assembled chrome://tracing dump (JSON)
  GET  /api/logs               — index of worker/job log files
  GET  /api/logs/<name>        — tail of one log file (text; ?lines=N)
  GET  /metrics                — Prometheus text (user + runtime metrics)
  GET  /                       — minimal human-readable HTML overview
  POST /api/jobs               — submit {entrypoint, ...} (job_submission)
  GET  /api/jobs/<id>          — job status
  POST /api/jobs/<id>/stop     — request stop
"""
from __future__ import annotations

import io
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

_dashboard: Optional["Dashboard"] = None


class Dashboard:
    def __init__(self, head, host: str = "127.0.0.1", port: int = 0):
        self.head = head
        dash = self

        class Handler(BaseHTTPRequestHandler):
            # stdlib logs every request to stderr by default — silence.
            def log_message(self, *a):  # noqa: D102
                pass

            def _send(self, code: int, body, ctype="application/json"):
                if isinstance(body, (dict, list)):
                    body = json.dumps(body, default=str)
                if isinstance(body, str):
                    body = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                try:
                    dash._route_get(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def do_POST(self):  # noqa: N802
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(n) if n else b"{}"
                    dash._route_post(self, json.loads(raw or b"{}"))
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="rtpu-dashboard", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ---------------- routing ----------------
    def _state(self, what: str):
        out = []
        self.head.req_state({"what": what}, out.append, None)
        return out[0]

    def _route_get(self, req):
        parsed = urlparse(req.path)
        path, q = parsed.path.rstrip("/") or "/", parse_qs(parsed.query)
        if path == "/":
            return req._send(200, self._overview_html(), "text/html")
        if path == "/metrics":
            from ray_tpu.util.metrics import prometheus_text

            return req._send(200,
                             prometheus_text() + self._node_metrics_text(),
                             "text/plain")
        if path == "/api/cluster":
            total, avail = [], []
            self.head.req_cluster_resources({}, total.append, None)
            self.head.req_cluster_resources({"available": True},
                                            avail.append, None)
            return req._send(200, {
                "resources_total": total[0],
                "resources_available": avail[0],
                "num_nodes": len(self._state("nodes")),
            })
        if path == "/api/summary":
            tasks = self._state("tasks")
            actors = self._state("actors")
            objs = self._state("objects")
            by_status: dict = {}
            for t in tasks:
                by_status[t["status"]] = by_status.get(t["status"], 0) + 1
            by_state: dict = {}
            for a in actors:
                by_state[a["state"]] = by_state.get(a["state"], 0) + 1
            return req._send(200, {
                "tasks": {"total": len(tasks), "by_status": by_status},
                "actors": {"total": len(actors), "by_state": by_state},
                "objects": {"total": len(objs),
                            "total_bytes": sum(o["size"] for o in objs)},
            })
        if path in ("/api/nodes", "/api/actors", "/api/tasks",
                    "/api/objects", "/api/jobs", "/api/named_actors"):
            what = path.rsplit("/", 1)[1]
            if what == "jobs":
                from ray_tpu.job_submission import _manager

                mgr = _manager(self.head, create=False)
                listed = self._state("jobs")
                if mgr is not None:
                    known = {j["job_id"] for j in listed}
                    listed += [j for j in mgr.list_jobs()
                               if j["job_id"] not in known]
                return req._send(200, listed)
            return req._send(200, self._state(what))
        if path == "/api/serve":
            from ray_tpu.serve.api import _deployments

            out = []
            # Snapshot: serve.run/delete mutate the dict from the driver
            # thread while this route serves from the HTTP thread.
            for name, dep in list(_deployments.items()):
                h = dep.handle
                entry = {"name": name,
                         "is_ingress": bool(getattr(dep, "is_ingress",
                                                    False)),
                         "autoscaling": dep.autoscaling_config or None}
                if h is not None:
                    entry.update(h.queue_stats())  # incl. num_replicas
                else:
                    entry["num_replicas"] = 0
                out.append(entry)
            return req._send(200, out)
        if path == "/traces" or path == "/api/traces":
            out = []
            limit = int(q.get("limit", ["50"])[0])
            self.head.req_traces({"limit": limit}, out.append, None)
            return req._send(200, out[0])
        if path == "/timeline" or path == "/api/timeline":
            from ray_tpu.observability.timeline import build_chrome_trace

            trace_id = (q.get("trace_id") or [None])[0]
            raw = []
            self.head.req_trace_timeline({"trace_id": trace_id},
                                         raw.append, None)
            return req._send(200, build_chrome_trace(raw[0]["tasks"],
                                                     raw[0]["spans"]))
        if path.startswith("/state/"):
            what = path[len("/state/"):]
            if what == "traces":
                out = []
                self.head.req_traces({}, out.append, None)
                return req._send(200, out[0])
            if what not in ("nodes", "actors", "tasks", "objects",
                            "jobs", "named_actors"):
                return req._send(404, {"error": f"no state table: {what}"})
            return req._send(200, self._state(what))
        if path == "/api/logs":
            return req._send(200, self._log_index())
        if path.startswith("/api/logs/"):
            name = os.path.basename(path[len("/api/logs/"):])
            lines = int(q.get("lines", ["200"])[0])
            logs_dir = os.path.join(self.head.session_dir, "logs")
            fp = os.path.join(logs_dir, name)
            if not os.path.exists(fp):
                return req._send(404, {"error": f"no such log: {name}"})
            return req._send(200, _tail(fp, lines), "text/plain")
        if path.startswith("/api/jobs/"):
            from ray_tpu.job_submission import _manager

            job_id = path.split("/")[3]
            mgr = _manager(self.head, create=False)
            info = mgr.get_job(job_id) if mgr else None
            if info is None:
                return req._send(404, {"error": f"no such job: {job_id}"})
            if path.endswith("/logs"):
                return req._send(200, mgr.get_logs(job_id), "text/plain")
            return req._send(200, info)
        return req._send(404, {"error": f"no route: {path}"})

    def _route_post(self, req, payload):
        path = urlparse(req.path).path.rstrip("/")
        from ray_tpu.job_submission import _manager

        if path == "/api/jobs":
            mgr = _manager(self.head, create=True)
            job_id = mgr.submit(
                payload["entrypoint"],
                submission_id=payload.get("submission_id"),
                runtime_env=payload.get("runtime_env"),
                metadata=payload.get("metadata"))
            return req._send(200, {"job_id": job_id})
        if path.startswith("/api/jobs/") and path.endswith("/stop"):
            job_id = path.split("/")[3]
            mgr = _manager(self.head, create=True)
            ok = mgr.stop(job_id)
            return req._send(200, {"stopped": ok})
        return req._send(404, {"error": f"no route: {path}"})

    # ---------------- views ----------------
    def _node_metrics_text(self) -> str:
        """Per-node usage gauges for the Prometheus scrape (reference:
        the reporter agent's node_cpu/node_mem series)."""
        import io

        buf = io.StringIO()
        names = {"cpu_percent": "node_cpu_percent",
                 "mem_used_bytes": "node_mem_used_bytes",
                 "mem_total_bytes": "node_mem_total_bytes",
                 "num_workers": "node_num_workers",
                 "store_used_bytes": "node_store_used_bytes",
                 "store_capacity_bytes": "node_store_capacity_bytes",
                 "store_num_objects": "node_store_num_objects"}
        for node in self._state("nodes"):
            nid = node["node_id"][:16]
            for key, metric in names.items():
                val = node.get("stats", {}).get(key)
                if val is not None:
                    buf.write(f'{metric}{{node="{nid}"}} {float(val)}\n')
        # Cluster-level recovery counters (node-loss plane): chaos runs
        # scrape these to assert recovery HAPPENED rather than infer it.
        from ray_tpu._private.recovery import recovery_stats

        for key, val in recovery_stats().items():
            buf.write(f"recovery_{key} {float(val)}\n")
        return buf.getvalue()

    def _log_index(self):
        logs_dir = os.path.join(self.head.session_dir, "logs")
        if not os.path.isdir(logs_dir):
            return []
        out = []
        for name in sorted(os.listdir(logs_dir)):
            fp = os.path.join(logs_dir, name)
            out.append({"name": name, "size": os.path.getsize(fp)})
        return out

    def _overview_html(self) -> str:
        """Server-rendered cluster overview: live resources, per-node
        stats, actors, jobs, and a task summary (reference scope: the
        dashboard's cluster/actors/jobs views — rendered server-side
        here instead of shipping a React bundle)."""
        total, avail = [], []
        self.head.req_cluster_resources({}, total.append, None)
        self.head.req_cluster_resources({"available": True}, avail.append,
                                        None)
        nodes = self._state("nodes")
        actors = self._state("actors")
        jobs = self._state("jobs")
        tasks_by_status: dict = {}
        for t in self._state("tasks"):
            tasks_by_status[t["status"]] = \
                tasks_by_status.get(t["status"], 0) + 1
        buf = io.StringIO()
        buf.write(
            "<html><head><title>ray_tpu dashboard</title>"
            "<meta http-equiv='refresh' content='5'>"
            "<style>body{font-family:monospace;margin:2em}"
            "table{border-collapse:collapse;margin-bottom:1.5em}"
            "td,th{border:1px solid #999;padding:4px 10px;text-align:left}"
            "th{background:#eee}</style></head><body>")
        buf.write("<h2>ray_tpu cluster</h2>")
        buf.write(f"<p>nodes: {len(nodes)} &middot; actors: {len(actors)} "
                  f"&middot; jobs: {len(jobs)} (auto-refreshes)</p>")

        import html as _html

        esc = _html.escape
        buf.write("<h3>resources</h3><table>"
                  "<tr><th>resource</th><th>available</th><th>total</th>"
                  "</tr>")
        for k, v in sorted(total[0].items()):
            # Custom resource names are user-controlled strings (e.g.
            # ray_tpu.init(resources={...})) — escape like actor/job fields.
            buf.write(f"<tr><td>{esc(str(k))}</td>"
                      f"<td>{avail[0].get(k, 0):g}</td>"
                      f"<td>{v:g}</td></tr>")
        buf.write("</table>")

        buf.write("<h3>nodes</h3><table><tr><th>node</th><th>alive</th>"
                  "<th>resources</th><th>cpu%</th><th>mem%</th>"
                  "<th>store used</th></tr>")
        for n in nodes:
            st = n.get("stats") or {}
            res = " ".join(f"{esc(str(k))}:{v:g}" for k, v in
                           sorted((n.get("resources") or {}).items())
                           if k != "memory")
            used = st.get("store_used_bytes")
            buf.write(
                f"<tr><td>{esc(str(n['node_id'])[:12])}</td>"
                f"<td>{'yes' if n.get('alive', True) else 'NO'}</td>"
                f"<td>{res}</td>"
                f"<td>{esc(str(st.get('cpu_percent', '-')))}</td>"
                f"<td>{esc(str(st.get('mem_percent', '-')))}</td>"
                f"<td>{_fmt_bytes(used) if used is not None else '-'}</td>"
                "</tr>")
        buf.write("</table>")

        if actors:
            buf.write("<h3>actors</h3><table><tr><th>actor</th>"
                      "<th>class</th><th>name</th><th>state</th>"
                      "<th>node</th><th>restarts</th></tr>")
            for a in actors[:100]:
                # User-controlled strings (class/actor names) must not
                # inject markup into the page.
                buf.write(
                    f"<tr><td>{esc(str(a.get('actor_id', ''))[:12])}</td>"
                    f"<td>{esc(str(a.get('class_name', '')))}</td>"
                    f"<td>{esc(str(a.get('name') or ''))}</td>"
                    f"<td>{esc(str(a.get('state', '')))}</td>"
                    f"<td>{esc(str(a.get('node_id') or '')[:12])}</td>"
                    f"<td>{a.get('num_restarts', 0)}</td></tr>")
            buf.write("</table>")

        if jobs:
            buf.write("<h3>jobs</h3><table><tr><th>job</th><th>status</th>"
                      "</tr>")
            for j in jobs[:50]:
                buf.write(f"<tr><td>{esc(str(j.get('job_id', '')))}</td>"
                          f"<td>{esc(str(j.get('status', '')))}</td></tr>")
            buf.write("</table>")

        if tasks_by_status:
            buf.write("<h3>tasks</h3><table><tr><th>status</th>"
                      "<th>count</th></tr>")
            for k, v in sorted(tasks_by_status.items()):
                buf.write(f"<tr><td>{k}</td><td>{v}</td></tr>")
            buf.write("</table>")

        buf.write("<p>JSON API: <a href='/api/cluster'>/api/cluster</a> "
                  "<a href='/api/nodes'>/api/nodes</a> "
                  "<a href='/api/actors'>/api/actors</a> "
                  "<a href='/api/tasks'>/api/tasks</a> "
                  "<a href='/api/objects'>/api/objects</a> "
                  "<a href='/api/jobs'>/api/jobs</a> "
                  "<a href='/api/summary'>/api/summary</a> "
                  "<a href='/api/logs'>/api/logs</a> "
                  "<a href='/metrics'>/metrics</a></p></body></html>")
        return buf.getvalue()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TB"


def _tail(path: str, lines: int) -> str:
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - 256 * 1024))
        data = f.read().decode(errors="replace")
    return "\n".join(data.splitlines()[-lines:])


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dashboard:
    """Start the dashboard against the in-process head (requires
    ray_tpu.init() to have booted a local head)."""
    global _dashboard
    import ray_tpu

    if _dashboard is not None:
        return _dashboard
    head = ray_tpu._head
    if head is None:
        raise RuntimeError("start_dashboard() requires a local head; call "
                           "ray_tpu.init() first")
    _dashboard = Dashboard(head, host, port)
    return _dashboard


def stop_dashboard():
    global _dashboard
    if _dashboard is not None:
        _dashboard.shutdown()
        _dashboard = None
